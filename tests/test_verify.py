"""Verification-tier tests (ISSUE 16): claim checks, audits, trust.

Unit tier: the same synchronous FakeServer rig as
test_scheduler_recovery.py drives the scheduler's event handlers
directly, so every verdict branch of the claim check and the audit
cross-check is pinned without timing — a Result is a CLAIM here, and
the tests play both honest and byzantine miners by hand.

Storm tier: seeded end-to-end byzantine storms over real UDP (the
chaos harness of test_chaos.py with ``ChaosMiner(byzantine=...)``),
asserting the acceptance property: a client never receives a wrong
``(hash, nonce)`` while any honest miner remains.
"""

import asyncio
import random

import pytest

from distributed_bitcoinminer_tpu.apps.scheduler import Scheduler
from distributed_bitcoinminer_tpu.bitcoin.hash import hash_op, scan_min
from distributed_bitcoinminer_tpu.bitcoin.message import Message, MsgType
from distributed_bitcoinminer_tpu.utils.config import (LeaseParams,
                                                       RetryParams,
                                                       VerifyParams)
from tests.test_scheduler_recovery import (CLIENT_X, FakeServer, MINER_A,
                                           MINER_B, MINER_C, join, request,
                                           result)

AUDIT_ALL = VerifyParams(enabled=True, audit_p=1.0,
                         audit_max_nonces=1 << 20)


def make_sched(verify=VerifyParams(), seed=7, **lease_kw):
    """Verify-tier scheduler over a recording fake server.

    ``verify`` is always passed explicitly so the suite is immune to
    the tier-1 matrix leg's DBM_VERIFY=0 environment; the seeded
    ``audit_rng`` makes every audit coin flip and window draw
    deterministic."""
    server = FakeServer()
    sched = Scheduler(server, lease=LeaseParams(**lease_kw),
                      verify=verify, audit_rng=random.Random(seed))
    return sched, server


def chunk_bounds(server, conn_id, n=0):
    """(lower, upper) of the n-th REQUEST granted to ``conn_id``."""
    m = server.sent_to(conn_id, MsgType.REQUEST)[n]
    return m.lower, m.upper


# ------------------------------------------------------------ claim checks


def test_honest_claim_accepted_and_counted():
    sched, server = make_sched()
    join(sched, MINER_A)
    request(sched, CLIENT_X, "honest work", 99)
    lo, hi = chunk_bounds(server, MINER_A)
    h, n = scan_min("honest work", lo, hi)
    result(sched, MINER_A, h=h, nonce=n)
    replies = server.sent_to(CLIENT_X, MsgType.RESULT)
    assert [(m.hash, m.nonce) for m in replies] == [(h, n)]
    assert sched.stats["claims_checked"] == 1
    assert sched.stats["claims_failed"] == 0
    assert sched.miners[0].trust == 1.0


def test_fabricated_hash_rejected_and_regranted():
    """A wrong-hash claim (the colluding-duplicates class too: the
    recompute never counts votes) is rejected before any merge state
    moves, the liar's trust decays, and the range re-executes on a
    different miner — the client still gets the true arg-min."""
    sched, server = make_sched()
    join(sched, MINER_A)
    join(sched, MINER_B)
    request(sched, CLIENT_X, "audit storm", 199)
    lo0, hi0 = chunk_bounds(server, MINER_A)
    lo1, hi1 = chunk_bounds(server, MINER_B)
    result(sched, MINER_A, h=1, nonce=lo0)       # fabricated: hash_op != 1
    assert server.sent_to(CLIENT_X, MsgType.RESULT) == []
    assert sched.stats["claims_failed"] == 1
    assert sched.stats["trust_decays_claim"] == 1
    liar = sched._find_miner(MINER_A)
    assert liar.trust == pytest.approx(0.25)
    # B is busy with its own chunk, so the rejected range parks and is
    # absorbed the moment B frees (the lease plane's park machinery).
    assert len(sched.parked) == 1
    h1, n1 = scan_min("audit storm", lo1, hi1)
    result(sched, MINER_B, h=h1, nonce=n1)       # B's own chunk
    retry = server.sent_to(MINER_B, MsgType.REQUEST)
    assert [(m.lower, m.upper) for m in retry] == [(lo1, hi1), (lo0, hi0)]
    h0, n0 = scan_min("audit storm", lo0, hi0)
    result(sched, MINER_B, h=h0, nonce=n0)       # the re-executed range
    replies = server.sent_to(CLIENT_X, MsgType.RESULT)
    assert [(m.hash, m.nonce) for m in replies] == \
        [scan_min("audit storm", 0, 200)]


def test_real_pair_outside_range_rejected():
    """A REAL (hash, nonce) lifted from outside the assigned range must
    not pass: the recompute alone would accept it."""
    sched, server = make_sched()
    join(sched, MINER_A)
    join(sched, MINER_B)
    request(sched, CLIENT_X, "range theft", 199)
    lo0, hi0 = chunk_bounds(server, MINER_A)
    lo1, hi1 = chunk_bounds(server, MINER_B)
    stolen = hi1 if hi1 > hi0 else hi0           # a nonce outside A's chunk
    assert not (lo0 <= stolen <= hi0)
    result(sched, MINER_A, h=hash_op("range theft", stolen), nonce=stolen)
    assert sched.stats["claims_failed"] == 1
    assert server.sent_to(CLIENT_X, MsgType.RESULT) == []


def test_difficulty_fabricated_qualifier_rejected():
    """Difficulty mode: a fabricated below-target hash must never enter
    the qualifying set (no early prefix release off a lie); the request
    parks with no spare miner and completes honestly off a joiner."""
    target = 1 << 40                             # ~never hit in 100 nonces
    sched, server = make_sched()
    join(sched, MINER_A)
    request(sched, CLIENT_X, "fake gold", 99, target=target)
    lo, hi = chunk_bounds(server, MINER_A)
    result(sched, MINER_A, h=5, nonce=lo + 3, target=target)  # "qualifies"
    assert server.sent_to(CLIENT_X, MsgType.RESULT) == []
    assert sched.stats["claims_failed"] == 1
    assert len(sched.parked) == 1                # no spare: range parks
    join(sched, MINER_B)                         # joiner absorbs it
    h, n = scan_min("fake gold", lo, hi)
    result(sched, MINER_B, h=h, nonce=n, target=target)
    replies = server.sent_to(CLIENT_X, MsgType.RESULT)
    assert [(m.hash, m.nonce) for m in replies] == [(h, n)]


# ------------------------------------------------------------------ audits


def test_audit_catches_sentinel_and_repairs_reply():
    """The sentinel-without-scan lie — a REAL in-range pair that is not
    the arg-min — passes the claim check by construction; only the
    audit re-execution can catch it. The reply HOLDS until every audit
    resolves, and the honest auditor's full-window find both convicts
    the liar and repairs the merged answer to the exact arg-min."""
    data = "audit storm"                         # global arg-min in chunk 0
    sched, server = make_sched(verify=AUDIT_ALL)
    join(sched, MINER_A)
    join(sched, MINER_B)
    request(sched, CLIENT_X, data, 199)
    lo0, hi0 = chunk_bounds(server, MINER_A)
    lo1, hi1 = chunk_bounds(server, MINER_B)
    h0, n0 = scan_min(data, lo0, hi0)
    assert (h0, n0) != (hash_op(data, lo0), lo0)  # the lie is not the min
    # A answers with the sentinel: real, in range, never scanned.
    result(sched, MINER_A, h=hash_op(data, lo0), nonce=lo0)
    assert sched.stats["claims_failed"] == 0     # claim check can't see it
    assert sched.stats["audits_issued"] == 1     # p=1: audit granted to B
    # B answers its own chunk honestly -> B's chunk audited on A (the
    # only disjoint miner). All chunks answered, but two holds remain.
    h1, n1 = scan_min(data, lo1, hi1)
    result(sched, MINER_B, h=h1, nonce=n1)
    assert sched.stats["audits_issued"] == 2
    assert server.sent_to(CLIENT_X, MsgType.RESULT) == []
    # A honestly re-executes B's window: the claim checks out.
    result(sched, MINER_A, h=h1, nonce=n1)
    assert sched.stats["audits_passed"] == 1
    assert server.sent_to(CLIENT_X, MsgType.RESULT) == []   # one hold left
    # B re-executes A's window and finds the true min: lie convicted,
    # answer repaired, last hold released -> the client sees the oracle.
    result(sched, MINER_B, h=h0, nonce=n0)
    assert sched.stats["audits_failed"] == 1
    assert sched.stats["trust_decays_audit"] == 1
    assert sched._find_miner(MINER_A).trust == pytest.approx(0.25)
    replies = server.sent_to(CLIENT_X, MsgType.RESULT)
    assert [(m.hash, m.nonce) for m in replies] == [scan_min(data, 0, 200)]


def test_byzantine_auditor_cannot_launder_a_lie():
    """An audit answered with a fabricated pair convicts the AUDITOR
    and re-issues the same subwindow to another disjoint miner — a
    byzantine auditor must not burn the only spot check on its
    accomplice's sentinel."""
    data = "audit storm"
    sched, server = make_sched(verify=AUDIT_ALL)
    for m in (MINER_A, MINER_B, MINER_C):
        join(sched, m)
    request(sched, CLIENT_X, data, 299)
    bounds = {m: chunk_bounds(server, m) for m in (MINER_A, MINER_B,
                                                   MINER_C)}
    lo0, hi0 = bounds[MINER_A]
    # A lies with the sentinel; the audit lands on the least-loaded
    # disjoint miner. B and C tie on load, so join order picks B.
    result(sched, MINER_A, h=hash_op(data, lo0), nonce=lo0)
    assert sched.stats["audits_issued"] == 1
    for m in (MINER_B, MINER_C):                 # honest own-chunk answers
        lo, hi = bounds[m]
        h, n = scan_min(data, lo, hi)
        result(sched, m, h=h, nonce=n)
    # B's FIFO now fronts the audit of A's window: B answers it with a
    # fabricated hash. B is convicted at the audit claim check and the
    # window re-audits on C instead of releasing the held reply.
    failed_before = sched.stats["claims_failed"]
    result(sched, MINER_B, h=1, nonce=lo0)
    assert sched.stats["claims_failed"] == failed_before + 1
    assert sched._find_miner(MINER_B).trust < 1.0
    assert sched.stats["audits_issued"] >= 4     # 3 first-issue + re-audit
    assert server.sent_to(CLIENT_X, MsgType.RESULT) == []
    # Drain every remaining audit honestly (C and A answer whatever
    # windows sit in their FIFOs) until the reply releases.
    pending = {m: 1 for m in (MINER_A, MINER_C)}
    for _ in range(8):
        if server.sent_to(CLIENT_X, MsgType.RESULT):
            break
        for m in (MINER_A, MINER_B, MINER_C):
            ms = sched._find_miner(m)
            if ms is None or not ms.pending:
                continue
            c = ms.pending[0]
            h, n = scan_min(data, c.lower, c.upper)
            result(sched, m, h=h, nonce=n)
    replies = server.sent_to(CLIENT_X, MsgType.RESULT)
    assert [(m.hash, m.nonce) for m in replies] == [scan_min(data, 0, 300)]
    assert sched.stats["audits_failed"] >= 1     # the sentinel was caught


def test_dead_auditor_releases_hold_as_inconclusive():
    """Liveness beats a spot check: when the auditor drops and no
    disjoint replacement exists, the audit records inconclusive and the
    held reply releases — the claim-checked merge stands."""
    data = "spot check"                          # global arg-min in chunk 1
    sched, server = make_sched(verify=AUDIT_ALL)
    join(sched, MINER_A)
    join(sched, MINER_B)
    request(sched, CLIENT_X, data, 199)
    lo0, hi0 = chunk_bounds(server, MINER_A)
    lo1, hi1 = chunk_bounds(server, MINER_B)
    h0, n0 = scan_min(data, lo0, hi0)
    result(sched, MINER_A, h=h0, nonce=n0)       # honest; audited on B
    h1, n1 = scan_min(data, lo1, hi1)
    result(sched, MINER_B, h=h1, nonce=n1)       # honest; audited on A
    assert sched.stats["audits_issued"] == 2
    assert server.sent_to(CLIENT_X, MsgType.RESULT) == []
    sched._on_drop(MINER_B)                      # auditor of chunk 0 dies
    # A is the suspect of that audit: no disjoint replacement exists.
    assert sched.stats["audits_inconclusive"] == 1
    # A's own outstanding audit (of B's chunk) still holds the reply...
    assert server.sent_to(CLIENT_X, MsgType.RESULT) == []
    result(sched, MINER_A, h=h1, nonce=n1)       # ...until A answers it
    assert sched.stats["audits_passed"] == 1
    replies = server.sent_to(CLIENT_X, MsgType.RESULT)
    assert [(m.hash, m.nonce) for m in replies] == [scan_min(data, 0, 200)]


# ------------------------------------------------------------------- trust


def test_trust_decay_recovery_curve_and_desperation():
    """The trust curve end to end: multiplicative decay to the floor,
    grant ineligibility below the bar, desperation dispatch flooring
    availability for a fully-distrusted pool, and additive recovery
    through confirmed work back above the bar."""
    sched, server = make_sched()
    join(sched, MINER_A)
    mp = sched.miner_plane
    ms = sched.miners[0]
    v = VerifyParams()
    assert ms.trust == 1.0 and not mp.distrusted(ms)
    assert mp.trust_fail(ms, "claim") == pytest.approx(0.25)
    assert not mp.distrusted(ms)                 # one strike: still in
    assert mp.trust_fail(ms, "audit") == pytest.approx(0.0625)
    assert mp.distrusted(ms)                     # two strikes: out
    for _ in range(10):
        mp.trust_fail(ms, "claim")
    assert ms.trust == v.trust_floor             # clamped, never zero
    assert sched.stats["trust_decays_claim"] == 11
    assert sched.stats["trust_decays_audit"] == 1
    # The whole pool is distrusted: desperation still grants (waiting
    # for nobody beats failing the request outright)...
    n_jobs = 4                       # distinct data: the result memo
    datas = [f"redemption {i}" for i in range(n_jobs)]  # replays repeats
    request(sched, CLIENT_X, datas[0], 99)
    assert len(server.sent_to(MINER_A, MsgType.REQUEST)) == 1
    assert sched.stats["desperation_dispatch"] >= 1
    # ...and each confirmed honest answer steps trust back up.
    seen = [ms.trust]
    for i, data in enumerate(datas):
        if i:
            request(sched, CLIENT_X, data, 99)
        lo, hi = chunk_bounds(server, MINER_A, n=i)
        h, n = scan_min(data, lo, hi)
        result(sched, MINER_A, h=h, nonce=n)
        seen.append(ms.trust)
    assert seen == sorted(seen)                  # monotone recovery
    assert ms.trust == pytest.approx(v.trust_floor
                                     + n_jobs * v.trust_recover)
    assert not mp.distrusted(ms)                 # back above the bar


def test_distrusted_miner_excluded_while_honest_pool_remains():
    sched, server = make_sched()
    join(sched, MINER_A)
    join(sched, MINER_B)
    mp = sched.miner_plane
    liar = sched._find_miner(MINER_A)
    mp.trust_fail(liar, "claim")
    mp.trust_fail(liar, "claim")
    assert mp.distrusted(liar)
    request(sched, CLIENT_X, "clean hands", 99)
    # The whole request lands on B; the distrusted miner gets nothing.
    assert server.sent_to(MINER_A, MsgType.REQUEST) == []
    assert len(server.sent_to(MINER_B, MsgType.REQUEST)) == 1
    lo, hi = chunk_bounds(server, MINER_B)
    h, n = scan_min("clean hands", lo, hi)
    result(sched, MINER_B, h=h, nonce=n)
    replies = server.sent_to(CLIENT_X, MsgType.RESULT)
    assert [(m.hash, m.nonce) for m in replies] == [(h, n)]
    assert sched.stats["desperation_dispatch"] == 0


# ------------------------------------------------------------- parity pin


class RawServer:
    """Records raw payload bytes — the byte-for-byte parity witness."""

    def __init__(self):
        self.writes = []

    def write(self, conn_id, payload):
        self.writes.append((conn_id, payload))


def _scripted_run(verify):
    """One fixed honest script against a given verify config; returns
    the raw write stream."""
    server = RawServer()
    sched = Scheduler(server, lease=LeaseParams(), verify=verify,
                      audit_rng=random.Random(3))
    join(sched, MINER_A)
    join(sched, MINER_B)
    request(sched, CLIENT_X, "parity pin", 199)
    reqs = {c: Message.from_json(p) for c, p in server.writes
            if c in (MINER_A, MINER_B)}
    for conn_id, m in reqs.items():
        h, n = scan_min("parity pin", m.lower, m.upper)
        result(sched, conn_id, h=h, nonce=n)
    return server.writes


def test_verify_off_is_bit_for_bit_stock(monkeypatch):
    """DBM_VERIFY=0 pins the stock believe-every-Result path: zero
    recomputes, zero trust bookkeeping, fabrications believed verbatim
    — and for honest traffic the claim-checks-on write stream is
    byte-identical to the stock one (checks reject, never mutate)."""
    monkeypatch.setenv("DBM_VERIFY", "0")
    server = FakeServer()
    sched = Scheduler(server, lease=LeaseParams(),
                      audit_rng=random.Random(3))   # verify from env
    assert not sched.verify.enabled
    join(sched, MINER_A)
    request(sched, CLIENT_X, "gullible", 99)
    result(sched, MINER_A, h=1, nonce=0)         # a lie, believed verbatim
    replies = server.sent_to(CLIENT_X, MsgType.RESULT)
    assert [(m.hash, m.nonce) for m in replies] == [(1, 0)]
    assert sched.stats["claims_checked"] == 0
    assert sched.stats["audits_issued"] == 0
    assert sched.miners[0].trust == 1.0
    # Byte-for-byte: same script, verify off vs claim-checks-on.
    off = _scripted_run(VerifyParams(enabled=False))
    on = _scripted_run(VerifyParams(enabled=True, audit_p=0.0))
    assert off == on


# ----------------------------------------------------- byzantine storms


@pytest.mark.parametrize("seed", [7, 21])
def test_byzantine_storm_never_answers_wrong(seed):
    """THE acceptance storm: a seeded byzantine schedule flips a
    wrong-hash liar and a sentinel liar on and off over real UDP while
    clients keep submitting; with one honest miner always present,
    every answer must be the exact oracle arg-min — claim checks kill
    the fabrications, audits + reply holds + repair merges kill the
    sentinels."""
    from distributed_bitcoinminer_tpu.lspnet import chaos
    from tests.test_chaos import ChaosCluster, expected, tight_lease

    async def scenario():
        chaos.seed_packet_faults(seed)
        async with ChaosCluster(lease=tight_lease()) as c:
            c.scheduler.verify = AUDIT_ALL
            await c.add_miner("wrong", byzantine="wrong_hash")
            await c.add_miner("sentinel", byzantine="sentinel")
            await c.add_miner("honest")
            schedule = chaos.generate_schedule(
                seed, 3.0, ["wrong", "sentinel"], episodes=4,
                kinds=("byzantine",))
            assert any(e.action == "byzantine" for e in schedule)
            storm = asyncio.create_task(chaos.run_schedule(
                schedule, c.miners))
            jobs = [("byz storm one", 399), ("byz storm two", 499),
                    ("byz storm three", 299)]
            retry = RetryParams(attempts=8, timeout_s=2.5, backoff_s=0.1,
                                backoff_cap_s=0.5)
            try:
                from distributed_bitcoinminer_tpu.apps.client import \
                    submit_with_retry
                for data, max_nonce in jobs:
                    got = await asyncio.wait_for(submit_with_retry(
                        c.hostport, data, max_nonce, 0, c.params, retry),
                        40)
                    assert got is not None, f"{data} never answered"
                    # Never a wrong pair — not even mid-storm.
                    assert got[:2] == expected(data, max_nonce)
            finally:
                await asyncio.wait_for(storm, 20)
            assert await c.settle(timeout=12.0)
            stats = c.scheduler.stats
            # The storm actually exercised the tier.
            assert stats["claims_checked"] > 0
            assert stats["audits_issued"] > 0
    asyncio.run(scenario())
