"""Pallas kernel tier: bit-exactness vs the host oracle.

Off-TPU the kernel is validated in the Mosaic TPU *simulator*
(``pltpu.InterpretParams``): it evaluates the kernel jaxpr op-by-op in
~1-2 s per grid step. (The generic ``interpret=True`` XLA path hands
XLA:CPU the whole grid program, whose compile blows up super-linearly on
SHA-shaped graphs — the root cause of round 2's "test file never
finishes".) On a real chip the same kernel lowers through Mosaic
(exercised by bench.py / the driver).

COST BUDGET (round-3, per VERDICT): every test here is sized in *grid
steps* and the whole file stays under ~10 steps (~1 min). Add steps only
with a matching cut elsewhere.

Ref parity: the kernel implements bitcoin/hash.go:13-17's op with
bitcoin/miner/miner.go:54-58's first-seen-wins tie rule.
"""

import numpy as np
import pytest

from distributed_bitcoinminer_tpu.bitcoin.hash import scan_min
from distributed_bitcoinminer_tpu.models import NonceSearcher
from distributed_bitcoinminer_tpu.models.miner_model import default_tier
from distributed_bitcoinminer_tpu.ops.sha256_host import sha256_midstate
from distributed_bitcoinminer_tpu.ops.sha256_jnp import build_tail_template
from distributed_bitcoinminer_tpu.ops.sha256_pallas import pallas_search_span


def _kernel_span(data: str, i0: int, lo: int, hi: int, k: int,
                 rows: int, nsteps: int, top: str = "", peel: bool = False):
    """Call the kernel the way the searcher does: every VALID nonce in
    [lo, hi] must have exactly ``k`` decimal digits (the searcher plans one
    dispatch per digit class — miner_model._digit_classes). Round 2's
    versions of these tests violated that (e.g. k=3 over [0, 511]) and
    "passed" only because the mis-formatted sub-width lanes happened not to
    win the argmin."""
    prefix = data.encode("utf-8") + b" " + top.encode("ascii")
    midstate, tail = sha256_midstate(prefix)
    template = build_tail_template(tail, k, len(prefix) + k)
    hi_h, lo_h, idx = pallas_search_span(
        np.asarray(midstate, np.uint32), template.astype(np.uint32),
        np.uint32(i0), np.uint32(lo), np.uint32(hi),
        rem=len(tail), k=k, rows=rows, nsteps=nsteps, interpret=True,
        peel=peel)
    return (int(hi_h) << 32) | int(lo_h), int(idx)


def test_kernel_exact_vs_oracle_single_step():
    # 256 lanes, window [100, 255]: lanes 0-99 masked low (1 step).
    got = _kernel_span("cmu440", i0=0, lo=100, hi=255, k=3, rows=2, nsteps=1)
    assert got == scan_min("cmu440", 100, 255)


def test_kernel_exact_vs_oracle_multi_step():
    # nsteps > 1 exercises the cross-step accumulator merge (2 steps).
    got = _kernel_span("pallas", i0=0, lo=100, hi=255, k=3, rows=1, nsteps=2)
    assert got == scan_min("pallas", 100, 255)


def test_kernel_masks_invalid_lanes():
    # Lanes run [128, 255]; window [130, 200] masks both ends (1 step).
    got = _kernel_span("mask", i0=128, lo=130, hi=200, k=3, rows=1, nsteps=1)
    assert got == scan_min("mask", 130, 200)


def test_kernel_two_block_tail():
    # Long message => 2-block tail template (the nblocks=2 kernel variant;
    # 1 step at double compression cost). Lanes [100, 227], all valid.
    data = "x" * 60
    got = _kernel_span(data, i0=100, lo=100, hi=227, k=3, rows=1, nsteps=1)
    assert got == scan_min(data, 100, 227)


def test_searcher_pallas_tier_exact():
    # One d=3 block, 512 lanes => a single grid step through the searcher.
    s = NonceSearcher("cmu440", batch=128, tier="pallas")
    assert s.search(100, 399) == scan_min("cmu440", 100, 399)


def test_searcher_pallas_tier_matches_jnp_tier():
    # Range confined to the d=3 digit class => one pallas step + jnp ref.
    sp = NonceSearcher("tier", batch=128, tier="pallas")
    sj = NonceSearcher("tier", batch=128, tier="jnp")
    assert sp.search(100, 299) == sj.search(100, 299)


def test_kernel_lowers_for_tpu_platform():
    """Pin TPU lowerability from the CPU suite: jax.export with
    platforms=['tpu'] runs the pallas->Mosaic lowering pass (where round
    2's illegal (1,3) output BlockSpec failed) without needing a chip, at
    the exact bench geometry. A regression here is the difference between
    a real BENCH pallas number and a silent jnp fallback."""
    import functools

    import jax
    import jax.export  # noqa: F401 — on jax 0.4.x the submodule is lazy:
    #                    bare `jax.export.export` raises AttributeError
    #                    until explicitly imported
    import jax.numpy as jnp

    args = (jnp.zeros(8, jnp.uint32), jnp.zeros((1, 16), jnp.uint32),
            jnp.uint32(0), jnp.uint32(0), jnp.uint32(0))
    for peel in (False, True):   # peeled variant must lower too (r5)
        f = functools.partial(pallas_search_span, rem=8, k=9, rows=8,
                              nsteps=16384, peel=peel)
        exported = jax.export.export(jax.jit(f), platforms=["tpu"])(*args)
        assert len(exported.mlir_module()) > 0


def test_default_tier_env(monkeypatch):
    # On this CPU test backend the platform-resolved default is jnp (the
    # pallas tier only wins — and only runs at speed — on a real chip).
    monkeypatch.delenv("DBM_COMPUTE", raising=False)
    assert default_tier() == "jnp"
    monkeypatch.setenv("DBM_COMPUTE", "PALLAS")
    assert default_tier() == "pallas"
    monkeypatch.setenv("DBM_COMPUTE", "JNP")
    assert default_tier() == "jnp"
    # Searcher-level values of the shared env var are NOT tier requests:
    # they resolve by platform (jnp off-chip), not crash the searcher.
    for v in ("auto", "jax", "host"):
        monkeypatch.setenv("DBM_COMPUTE", v)
        assert default_tier() == "jnp"
        NonceSearcher("x", batch=128)   # constructs fine
    monkeypatch.setenv("DBM_COMPUTE", "bogus")
    with pytest.raises(ValueError):
        NonceSearcher("x", batch=128)


def test_until_kernel_first_qualifying_vs_oracle():
    """Difficulty mode on the Mosaic kernel (interpret): the 4th
    accumulator must yield the FIRST qualifying nonce, not the argmin,
    across a multi-step grid; the fallback argmin must match the plain
    kernel when nothing qualifies."""
    from distributed_bitcoinminer_tpu.bitcoin.hash import hash_op, scan_min
    data, lo, hi = "untilpal", 128, 511   # one 3-digit block, 3 batches
    s = NonceSearcher(data, batch=128, tier="pallas")
    hashes = {n: hash_op(data, n) for n in range(lo, hi + 1)}
    # target reachable only in the last sub-dispatch's lanes
    target = min(h for n, h in hashes.items() if n >= 384) + 1
    first = next(n for n in range(lo, hi + 1) if hashes[n] < target)
    assert s.search_until(lo, hi, target) == (hashes[first], first, True)
    # unreachable target -> exact argmin fallback, found=False
    wh, wn = scan_min(data, lo, hi)
    assert s.search_until(lo, hi, min(hashes.values())) == (wh, wn, False)


def test_two_block_tail_with_hoist_straddling_boundary():
    """Long data (2-block tail: 2 device compressions/nonce) with the r5 digit
    hoist ACTIVE (k=9, one 1024-lane step => m=4) over a window that
    straddles a 10^4 boundary at lane offset 500 — BOTH candidates of
    the hoist's two-candidate select execute, on the geometry the rows
    sweep has not yet covered on-chip (VERDICT r4 weak 5). Budget note:
    one rows=8 step at 2 compressions ~ 1 plain 2048-lane step."""
    long_data = "x" * 57
    prefix = long_data.encode() + b" "
    mid, tail = sha256_midstate(prefix)
    k = 9
    tp = build_tail_template(tail, k, len(prefix) + k).astype(np.uint32)
    assert tp.shape[0] == 2
    lo = 123_459_500           # boundary 123_460_000 = lo + 500 < lo + 1024
    hi = lo + 1024 - 1
    got = pallas_search_span(np.asarray(mid, np.uint32), tp, np.uint32(lo),
                             np.uint32(lo), np.uint32(hi),
                             rem=len(tail), k=k, rows=8, nsteps=1,
                             interpret=True)
    h, low, idx = (int(x) for x in got)
    want = scan_min(long_data, lo, hi)
    assert ((h << 32) | low, idx) == want
    # The straddle premise itself, so a future constant change can't
    # silently turn this back into a single-candidate test.
    assert lo < (lo // 10_000 + 1) * 10_000 <= hi


def test_peeled_kernel_exact_masked_and_two_block():
    """Round-5 peeled compression: rounds 0-15 run as straight-line code
    (no block-0 schedule ``where`` waste) and rounds before the first
    digit-carrying word ride the scalar plane. Must be bit-exact on a
    masked multi-step window and on the 2-block tail, where the scalar
    prefix is deepest (rem=61 -> 15 scalar rounds). Budget: 2 steps + 1
    double-compression step."""
    got = _kernel_span("peel", i0=0, lo=130, hi=255, k=3, rows=1, nsteps=2,
                       peel=True)
    assert got == scan_min("peel", 130, 255)
    data = "x" * 60
    got = _kernel_span(data, i0=100, lo=100, hi=227, k=3, rows=1, nsteps=1,
                       peel=True)
    assert got == scan_min(data, 100, 227)


def test_peeled_until_kernel_vs_oracle():
    """The until variant of the peeled kernel: first-qualifying semantics
    and the argmin fallback both intact (the SMEM flag plumbing wraps the
    same peeled body). Budget: 2 steps x 2 legs."""
    from distributed_bitcoinminer_tpu.bitcoin.hash import hash_op
    from distributed_bitcoinminer_tpu.ops.sha256_pallas import (
        pallas_search_span_until)
    data, lo, hi = "untilpeel", 128, 383
    prefix = data.encode() + b" "
    mid, tail = sha256_midstate(prefix)
    tp = build_tail_template(tail, 3, len(prefix) + 3).astype(np.uint32)
    hashes = {n: hash_op(data, n) for n in range(lo, hi + 1)}
    target = sorted(hashes.values())[3] + 1     # a few qualifying nonces
    first = next(n for n in range(lo, hi + 1) if hashes[n] < target)

    def run(t):
        return tuple(int(x) for x in pallas_search_span_until(
            np.asarray(mid, np.uint32), tp, np.uint32(128), np.uint32(lo),
            np.uint32(hi), np.uint32(t >> 32), np.uint32(t & 0xFFFFFFFF),
            rem=len(tail), k=3, rows=1, nsteps=2, interpret=True,
            peel=True))

    found, f_idx, _, _, _ = run(target)
    assert (found, f_idx) == (1, first)
    wh, wn = scan_min(data, lo, hi)
    found, _, b_hi, b_lo, b_idx = run(min(hashes.values()))  # unreachable
    assert found == 0 and ((b_hi << 32) | b_lo, b_idx) == (wh, wn)
