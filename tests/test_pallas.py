"""Pallas kernel tier: bit-exactness vs the host oracle.

Off-TPU the fully-unrolled kernel is validated in *eager interpret* mode
(``jax.disable_jit()`` + ``interpret=True``): letting XLA:CPU compile the
jitted unrolled 64-round chain blows up superlinearly, while the eager
interpreter evaluates the same kernel math in seconds. On a real chip the
same code paths lower through Mosaic (exercised by bench.py / the driver).

Ref parity: the kernel implements bitcoin/hash.go:13-17's op with
bitcoin/miner/miner.go:54-58's first-seen-wins tie rule.
"""

import os

import jax
import numpy as np
import pytest

from distributed_bitcoinminer_tpu.bitcoin.hash import scan_min
from distributed_bitcoinminer_tpu.models import NonceSearcher
from distributed_bitcoinminer_tpu.models.miner_model import default_tier
from distributed_bitcoinminer_tpu.ops.sha256_host import sha256_midstate
from distributed_bitcoinminer_tpu.ops.sha256_jnp import build_tail_template
from distributed_bitcoinminer_tpu.ops.sha256_pallas import pallas_search_span


def _kernel_span(data: str, i0: int, lo: int, hi: int, k: int,
                 rows: int, nsteps: int, top: str = ""):
    prefix = data.encode("utf-8") + b" " + top.encode("ascii")
    midstate, tail = sha256_midstate(prefix)
    template = build_tail_template(tail, k, len(prefix) + k)
    with jax.disable_jit():
        hi_h, lo_h, idx = pallas_search_span(
            np.asarray(midstate, np.uint32), template.astype(np.uint32),
            np.uint32(i0), np.uint32(lo), np.uint32(hi),
            rem=len(tail), k=k, rows=rows, nsteps=nsteps, interpret=True)
    return (int(hi_h) << 32) | int(lo_h), int(idx)


def test_kernel_exact_vs_oracle_single_step():
    got = _kernel_span("cmu440", i0=0, lo=100, hi=355, k=3, rows=2, nsteps=1)
    assert got == scan_min("cmu440", 100, 355)


def test_kernel_exact_vs_oracle_multi_step():
    # nsteps > 1 exercises the per-step partial rows + cross-step argmin.
    got = _kernel_span("pallas", i0=0, lo=0, hi=511, k=3, rows=1, nsteps=4)
    assert got == scan_min("pallas", 0, 511)


def test_kernel_masks_invalid_lanes():
    # Window strictly inside the lane span: lanes outside [lo, hi] must not
    # contribute even when their hashes would win.
    got = _kernel_span("mask", i0=0, lo=130, hi=200, k=3, rows=1, nsteps=2)
    assert got == scan_min("mask", 130, 200)


def test_kernel_two_block_tail():
    # Long message => 2-block tail template (the nblocks=2 kernel variant).
    data = "x" * 60
    got = _kernel_span(data, i0=0, lo=0, hi=255, k=3, rows=1, nsteps=2)
    assert got == scan_min(data, 0, 255)


def test_searcher_pallas_tier_exact():
    s = NonceSearcher("cmu440", batch=128, tier="pallas")
    assert s.search(100, 399) == scan_min("cmu440", 100, 399)


def test_searcher_pallas_tier_matches_jnp_tier():
    sp = NonceSearcher("tier", batch=128, tier="pallas")
    sj = NonceSearcher("tier", batch=128, tier="jnp")
    assert sp.search(0, 299) == sj.search(0, 299)


def test_default_tier_env(monkeypatch):
    monkeypatch.delenv("DBM_COMPUTE", raising=False)
    assert default_tier() == "jnp"
    monkeypatch.setenv("DBM_COMPUTE", "PALLAS")
    assert default_tier() == "pallas"
    monkeypatch.setenv("DBM_COMPUTE", "bogus")
    with pytest.raises(ValueError):
        NonceSearcher("x", batch=128)
