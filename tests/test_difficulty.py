"""Difficulty-target mode (BASELINE config 5): in-kernel early exit and the
streaming client, checked against the host oracle."""

import asyncio

from distributed_bitcoinminer_tpu.bitcoin.hash import MAX_U64, hash_op, scan_min
from distributed_bitcoinminer_tpu.models import (NonceSearcher,
                                                 ShardedNonceSearcher)


def first_below(data, lower, upper, target):
    for n in range(lower, upper + 1):
        h = hash_op(data, n)
        if h < target:
            return h, n, True
    return (*scan_min(data, lower, upper), False)


def test_search_until_finds_first_qualifying_nonce():
    data = "difficulty"
    s = NonceSearcher(data, batch=128)
    # A loose target hits quickly; the FIRST qualifying nonce must match a
    # sequential oracle scan, not the global argmin.
    target = 1 << 59
    assert s.search_until(0, 4095, target) == first_below(data, 0, 4095, target)


def test_search_until_miss_falls_back_to_argmin():
    data = "no luck"
    s = NonceSearcher(data, batch=64)
    got = s.search_until(100, 1500, 1)  # impossible target
    assert got == (*scan_min(data, 100, 1500), False)


def test_search_until_crosses_blocks():
    data = "cmu440"
    s = NonceSearcher(data, batch=64)
    target = 1 << 56  # ~1/256 per nonce; usually needs a few hundred nonces
    assert s.search_until(0, 99999, target) == \
        first_below(data, 0, 99999, target)


class TestShardedDifficulty:
    """VERDICT r2 task 6: the mesh-sharded difficulty scan must preserve
    first-qualifying-nonce semantics across the 8-device CPU mesh."""

    def test_sharded_search_until_matches_sequential_oracle(self):
        data = "difficulty"
        s = ShardedNonceSearcher(data, batch=64)
        assert s.n_devices == 8
        target = 1 << 59
        assert s.search_until(0, 4095, target) == \
            first_below(data, 0, 4095, target)

    def test_sharded_search_until_matches_single_device(self):
        # The hit usually lands mid-span on a non-first device; both
        # dispatch shapes must report the identical first hit.
        data = "cmu440"
        target = 1 << 56
        sh = ShardedNonceSearcher(data, batch=64)
        sd = NonceSearcher(data, batch=64)
        assert sh.search_until(0, 49999, target) == \
            sd.search_until(0, 49999, target)

    def test_sharded_miss_falls_back_to_argmin(self):
        data = "no luck"
        s = ShardedNonceSearcher(data, batch=64)
        got = s.search_until(100, 1500, 1)  # impossible target
        assert got == (*scan_min(data, 100, 1500), False)


class TestScanUntilOracles:
    """bitcoin.scan_until is the host oracle for every until tier; the
    native C++ scan must agree bit-for-bit, including the miss fallback."""

    def test_scan_until_matches_sequential_definition(self):
        from distributed_bitcoinminer_tpu.bitcoin.hash import scan_until
        assert scan_until("difficulty", 0, 4095, 1 << 59) == \
            first_below("difficulty", 0, 4095, 1 << 59)
        assert scan_until("no luck", 100, 1500, 1) == \
            first_below("no luck", 100, 1500, 1)

    def test_native_scan_until_parity(self):
        from distributed_bitcoinminer_tpu import native
        from distributed_bitcoinminer_tpu.bitcoin.hash import scan_until
        for data, lo, hi, target in [
                ("difficulty", 0, 4095, 1 << 59),     # quick hit
                ("cmu440", 0, 20000, 1 << 56),        # hit deeper in
                ("no luck", 100, 1500, 1),            # miss -> argmin
                ("edge", 7, 7, MAX_U64)]:             # 1-nonce, any hash wins
            assert native.scan_until_native(data, lo, hi, target) == \
                scan_until(data, lo, hi, target)

    def test_native_scan_until_empty_range_raises(self):
        import pytest
        from distributed_bitcoinminer_tpu import native
        with pytest.raises(ValueError):
            native.scan_until_native("x", 5, 3, 1 << 60)


class TestUntilTierDegradation:
    """A pallas until-tier failure (e.g. a Mosaic lowering regression in
    the SMEM-flag early-exit kernel, which is newer than the argmin
    kernel) must degrade the searcher to the jnp until tier — exact same
    contract — instead of killing difficulty mode."""

    def test_single_device_degrades_and_stays_exact(self, monkeypatch):
        from distributed_bitcoinminer_tpu.ops import sha256_pallas

        def boom(*a, **k):
            raise RuntimeError("synthetic Mosaic lowering failure")
        monkeypatch.setattr(sha256_pallas, "pallas_until", boom)
        s = NonceSearcher("degrade", batch=128, tier="pallas")
        target = 1 << 58
        assert s.search_until(0, 2999, target) == \
            first_below("degrade", 0, 2999, target)
        assert s._until_degraded
        # Argmin path is untouched by the degradation flag.
        assert s.search(0, 499) == scan_min("degrade", 0, 499)

    def test_sharded_degrades_and_stays_exact(self, monkeypatch):
        from distributed_bitcoinminer_tpu.parallel import mesh_search

        real = mesh_search.sharded_search_span_until
        calls = {"pallas": 0}

        def flaky(*a, **k):
            if k.get("tier") == "pallas":
                calls["pallas"] += 1
                raise RuntimeError("synthetic Mosaic lowering failure")
            return real(*a, **k)
        # Patch at the module models.sharded imports from.
        import distributed_bitcoinminer_tpu.models.sharded as sharded_mod
        monkeypatch.setattr(sharded_mod, "sharded_search_span_until", flaky)
        s = ShardedNonceSearcher("degrade", batch=64, tier="pallas")
        target = 1 << 58
        assert s.search_until(0, 2999, target) == \
            first_below("degrade", 0, 2999, target)
        assert s._until_degraded
        assert calls["pallas"] == 1  # sticky: no per-sub retry storm


class UntilOracleSearcher:
    """Host-oracle searcher speaking the until protocol (optionally slow),
    standing in for a TPU miner in cluster tests."""

    def __init__(self, data: str, delay: float = 0.0):
        self.data = data
        self.delay = delay

    def search(self, lower: int, upper: int):
        if self.delay:
            import time
            time.sleep(self.delay)
        return scan_min(self.data, lower, upper)

    def search_until(self, lower: int, upper: int, target: int):
        if self.delay:
            import time
            time.sleep(self.delay)
        from distributed_bitcoinminer_tpu.bitcoin.hash import scan_until
        return scan_until(self.data, lower, upper, target)


def until_factory(delay: float = 0.0):
    return lambda data, batch: UntilOracleSearcher(data, delay)


class TestSubmitUntilEndToEnd:
    """VERDICT r3 task 1: the difficulty target rides the Request through
    scheduler and miners, which run search_until and early-exit; the merged
    Result is the globally FIRST qualifying nonce, bit-exact vs the oracle.

    Oracles scan [0, max_nonce+1]: the system's preserved bound quirk (the
    scheduler sends exclusive uppers, miners read them inclusively)."""

    def test_multi_miner_first_qualifying_exact(self):
        from distributed_bitcoinminer_tpu.apps.client import submit_until
        from distributed_bitcoinminer_tpu.bitcoin.hash import scan_until
        from tests.test_apps import Cluster, fast_params

        data, max_nonce, target = "threaded target", 2999, 1 << 58
        want = scan_until(data, 0, max_nonce + 1, target)
        assert want[2], "test needs a target the range actually hits"

        async def scenario():
            async with Cluster(fast_params()) as c:
                for _ in range(3):
                    await c.start_miner(factory=until_factory())
                got = await asyncio.wait_for(
                    submit_until(c.hostport, data, max_nonce, target,
                                 c.params), 20)
                assert got == want
        asyncio.run(scenario())

    def test_model_searcher_runs_in_kernel_until(self):
        """The flagship path: a real model searcher (device dispatch via
        ops.search / pallas tiers) behind the miner, driven end-to-end
        through the wire protocol with a target."""
        from distributed_bitcoinminer_tpu.apps.client import submit_until
        from distributed_bitcoinminer_tpu.bitcoin.hash import scan_until
        from tests.test_apps import Cluster, fast_params

        data, max_nonce, target = "kernel until", 3999, 1 << 58
        want = scan_until(data, 0, max_nonce + 1, target)
        assert want[2]

        async def scenario():
            async with Cluster(fast_params()) as c:
                await c.start_miner(
                    factory=lambda d, b: NonceSearcher(d, batch=256))
                got = await asyncio.wait_for(
                    submit_until(c.hostport, data, max_nonce, target,
                                 c.params), 60)
                assert got == want
        asyncio.run(scenario())

    def test_unreachable_target_degrades_to_exact_argmin(self):
        from distributed_bitcoinminer_tpu.apps.client import submit_until
        from tests.test_apps import Cluster, fast_params

        async def scenario():
            async with Cluster(fast_params()) as c:
                for _ in range(2):
                    await c.start_miner(factory=until_factory())
                got = await asyncio.wait_for(
                    submit_until(c.hostport, "impossible", 1499, 1,
                                 c.params), 20)
                assert got == (*scan_min("impossible", 0, 1500), False)
        asyncio.run(scenario())

    def test_stock_miners_still_answer_target_requests(self, caplog):
        """Miners WITHOUT the until mode (the stock-Go-miner shape: the
        Target key is dropped, chunks full-scan) must still produce a valid
        qualifying Result — the chunk arg-min qualifies whenever anything
        in the chunk does, just not necessarily the first such nonce. The
        scheduler detects the missing target echo and surfaces the weaker
        guarantee in its log (ADVICE r4)."""
        import logging

        from distributed_bitcoinminer_tpu.apps.client import submit_until
        from tests.test_apps import Cluster, fast_params, oracle_factory

        data, max_nonce, target = "mixed pool", 2999, 1 << 58

        async def scenario():
            async with Cluster(fast_params()) as c:
                for _ in range(2):
                    await c.start_miner(factory=oracle_factory())
                got = await asyncio.wait_for(
                    submit_until(c.hostport, data, max_nonce, target,
                                 c.params), 20)
                assert got is not None
                g_hash, g_nonce, found = got
                assert found and g_hash < target
                assert g_hash == hash_op(data, g_nonce)
        with caplog.at_level(logging.INFO, logger="dbm.scheduler"):
            asyncio.run(scenario())
        assert any("without the target extension" in r.message
                   for r in caplog.records), "weak-guarantee log missing"

    def test_target_chunk_survives_miner_drop(self):
        """A dropped miner's chunk is reassigned WITH its target (the chunk
        record carries it), so the recovered request still answers the
        exact first-qualifying nonce."""
        from distributed_bitcoinminer_tpu.apps.client import submit_until
        from distributed_bitcoinminer_tpu.bitcoin.hash import scan_until
        from tests.test_apps import Cluster, fast_params

        data, max_nonce, target = "fault target", 2399, 1 << 58
        want = scan_until(data, 0, max_nonce + 1, target)
        assert want[2]

        async def scenario():
            params = fast_params(epoch_ms=40, limit=3)
            async with Cluster(params) as c:
                victim = await c.start_miner(factory=until_factory(delay=1.5))
                await c.start_miner(factory=until_factory())
                pending = asyncio.create_task(
                    submit_until(c.hostport, data, max_nonce, target, params))
                await asyncio.sleep(0.3)   # both miners hold target chunks
                victim.client._conn.abort()
                victim.client._ep.close()
                assert await asyncio.wait_for(pending, 20) == want
        asyncio.run(scenario())

    def test_poison_target_request_does_not_drain_pool(self):
        """A hand-rolled Request with Target >= 2^64 must be dropped at the
        codec (like Go's json.Unmarshal would), not fan out and crash every
        until-capable miner in turn (code-review r4)."""
        from distributed_bitcoinminer_tpu.apps.client import submit_until
        from distributed_bitcoinminer_tpu.bitcoin.hash import scan_until
        from distributed_bitcoinminer_tpu.lsp.client import new_async_client
        from tests.test_apps import Cluster, fast_params

        data, max_nonce, target = "after poison", 1999, 1 << 58
        want = scan_until(data, 0, max_nonce + 1, target)

        async def scenario():
            async with Cluster(fast_params()) as c:
                for _ in range(2):
                    await c.start_miner(factory=until_factory())
                poisoner = await new_async_client(c.hostport, c.params)
                poisoner.write(
                    b'{"Type":1,"Data":"boom","Lower":0,"Upper":500,'
                    b'"Hash":0,"Nonce":0,"Target":18446744073709551616}')
                await asyncio.sleep(0.3)  # scheduler reads + drops it
                # Pool must be intact and serving difficulty requests.
                got = await asyncio.wait_for(
                    submit_until(c.hostport, data, max_nonce, target,
                                 c.params), 20)
                assert got == want
                await poisoner.close()
        asyncio.run(scenario())

    def test_prefix_release_beats_slow_pool(self):
        """VERDICT r4 task 2: with a 3-miner pool and a target only chunk 0
        can hit, the Result releases at chunk 0's hit — the scheduler must
        NOT hold the all-chunks barrier while the other miners full-scan
        their non-hitting chunks."""
        import time

        from distributed_bitcoinminer_tpu.apps.client import submit_until
        from distributed_bitcoinminer_tpu.bitcoin.hash import scan_until
        from tests.test_apps import Cluster, fast_params

        data, max_nonce = "chunk zero", 2999
        # Chunks (3 miners): [0,1000], [1000,2000], [2000,3000] inclusive.
        # Pick the target so qualifying hashes exist ONLY in chunk 0: any
        # hash strictly below the best of chunks 1-2.
        target = min(hash_op(data, n) for n in range(1000, 3001))
        h0 = min(hash_op(data, n) for n in range(0, 1001))
        assert h0 < target, "test needs chunk 0 to hold the global min"
        want = scan_until(data, 0, max_nonce + 1, target)
        assert want[2]
        slow = 2.5

        async def scenario():
            async with Cluster(fast_params()) as c:
                # Join order is chunk order: the fast miner gets chunk 0.
                await c.start_miner(factory=until_factory())
                for _ in range(2):
                    await c.start_miner(factory=until_factory(delay=slow))
                t0 = time.monotonic()
                got = await asyncio.wait_for(
                    submit_until(c.hostport, data, max_nonce, target,
                                 c.params), 20)
                elapsed = time.monotonic() - t0
                assert got == want
                # TTFH ~ chunk 0's scan, not the slow miners' stalls.
                assert elapsed < slow * 0.6, elapsed
        asyncio.run(scenario())

    def test_prefix_release_waits_for_earlier_chunk(self):
        """The prefix guard: a qualifying hit in chunk 1 arriving FIRST
        (chunk 0's miner is slow) must not release early — chunk 0 also
        hits at a lower nonce, and the answer must be the global first."""
        from distributed_bitcoinminer_tpu.apps.client import submit_until
        from distributed_bitcoinminer_tpu.bitcoin.hash import scan_until
        from tests.test_apps import Cluster, fast_params

        data, max_nonce, target = "early exit", 2999, 1 << 59
        want = scan_until(data, 0, max_nonce + 1, target)
        # Precondition: both the first and a later chunk qualify, so a
        # premature release would answer the wrong (higher) nonce.
        assert want[2] and want[1] <= 1000
        later = scan_until(data, 1500, 3000, target)
        assert later[2] and later[1] != want[1]

        async def scenario():
            async with Cluster(fast_params()) as c:
                await c.start_miner(factory=until_factory(delay=0.8))
                await c.start_miner(factory=until_factory())
                got = await asyncio.wait_for(
                    submit_until(c.hostport, data, max_nonce, target,
                                 c.params), 20)
                assert got == want
        asyncio.run(scenario())

    def test_loose_target_completes_measurably_earlier(self):
        """The whole point of threading the target: an until request on the
        same range finishes well ahead of the full arg-min scan because the
        miners stop at their first hit instead of scanning everything."""
        import time

        from distributed_bitcoinminer_tpu.apps.client import submit, submit_until
        from tests.test_apps import Cluster, fast_params

        data, max_nonce, target = "early exit", 299_999, 1 << 59

        async def scenario():
            async with Cluster(fast_params()) as c:
                await c.start_miner(factory=until_factory())
                t0 = time.monotonic()
                full = await asyncio.wait_for(
                    submit(c.hostport, data, max_nonce, c.params), 120)
                t_full = time.monotonic() - t0
                t0 = time.monotonic()
                until = await asyncio.wait_for(
                    submit_until(c.hostport, data, max_nonce, target,
                                 c.params), 120)
                t_until = time.monotonic() - t0
                assert full is not None and until is not None
                assert until[2] and until[0] < target
                # Python-oracle miner: the full scan hashes 300k nonces,
                # the until scan ~2^5 (target ~= 1/32 per nonce) — orders
                # of magnitude apart; 2x is a flake-proof floor.
                assert t_until < t_full / 2, (t_until, t_full)
        asyncio.run(scenario())


def test_stream_until_end_to_end():
    from distributed_bitcoinminer_tpu.apps.client import stream_until
    from tests.test_apps import Cluster, fast_params

    async def scenario():
        async with Cluster(fast_params()) as c:
            await c.start_miner()
            target = 1 << 57
            got = await asyncio.wait_for(
                stream_until(c.hostport, "stream", target, span=500,
                             params=c.params), 20)
            assert got is not None
            g_hash, g_nonce, spans = got
            assert g_hash < target
            assert g_hash == hash_op("stream", g_nonce)
            # The winning nonce lies in the last span streamed; every prior
            # span's full scan (incl. its +1 quirk nonce) missed the target.
            lo = (spans - 1) * 500
            assert lo <= g_nonce <= spans * 500
            for n in range(0, lo):
                assert hash_op("stream", n) >= target
    asyncio.run(scenario())
