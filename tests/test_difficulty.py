"""Difficulty-target mode (BASELINE config 5): in-kernel early exit and the
streaming client, checked against the host oracle."""

import asyncio

from distributed_bitcoinminer_tpu.bitcoin.hash import MAX_U64, hash_op, scan_min
from distributed_bitcoinminer_tpu.models import (NonceSearcher,
                                                 ShardedNonceSearcher)


def first_below(data, lower, upper, target):
    for n in range(lower, upper + 1):
        h = hash_op(data, n)
        if h < target:
            return h, n, True
    return (*scan_min(data, lower, upper), False)


def test_search_until_finds_first_qualifying_nonce():
    data = "difficulty"
    s = NonceSearcher(data, batch=128)
    # A loose target hits quickly; the FIRST qualifying nonce must match a
    # sequential oracle scan, not the global argmin.
    target = 1 << 59
    assert s.search_until(0, 4095, target) == first_below(data, 0, 4095, target)


def test_search_until_miss_falls_back_to_argmin():
    data = "no luck"
    s = NonceSearcher(data, batch=64)
    got = s.search_until(100, 1500, 1)  # impossible target
    assert got == (*scan_min(data, 100, 1500), False)


def test_search_until_crosses_blocks():
    data = "cmu440"
    s = NonceSearcher(data, batch=64)
    target = 1 << 56  # ~1/256 per nonce; usually needs a few hundred nonces
    assert s.search_until(0, 99999, target) == \
        first_below(data, 0, 99999, target)


class TestShardedDifficulty:
    """VERDICT r2 task 6: the mesh-sharded difficulty scan must preserve
    first-qualifying-nonce semantics across the 8-device CPU mesh."""

    def test_sharded_search_until_matches_sequential_oracle(self):
        data = "difficulty"
        s = ShardedNonceSearcher(data, batch=64)
        assert s.n_devices == 8
        target = 1 << 59
        assert s.search_until(0, 4095, target) == \
            first_below(data, 0, 4095, target)

    def test_sharded_search_until_matches_single_device(self):
        # The hit usually lands mid-span on a non-first device; both
        # dispatch shapes must report the identical first hit.
        data = "cmu440"
        target = 1 << 56
        sh = ShardedNonceSearcher(data, batch=64)
        sd = NonceSearcher(data, batch=64)
        assert sh.search_until(0, 49999, target) == \
            sd.search_until(0, 49999, target)

    def test_sharded_miss_falls_back_to_argmin(self):
        data = "no luck"
        s = ShardedNonceSearcher(data, batch=64)
        got = s.search_until(100, 1500, 1)  # impossible target
        assert got == (*scan_min(data, 100, 1500), False)


def test_stream_until_end_to_end():
    from distributed_bitcoinminer_tpu.apps.client import stream_until
    from tests.test_apps import Cluster, fast_params

    async def scenario():
        async with Cluster(fast_params()) as c:
            await c.start_miner()
            target = 1 << 57
            got = await asyncio.wait_for(
                stream_until(c.hostport, "stream", target, span=500,
                             params=c.params), 20)
            assert got is not None
            g_hash, g_nonce, spans = got
            assert g_hash < target
            assert g_hash == hash_op("stream", g_nonce)
            # The winning nonce lies in the last span streamed; every prior
            # span's full scan (incl. its +1 quirk nonce) missed the target.
            lo = (spans - 1) * 500
            assert lo <= g_nonce <= spans * 500
            for n in range(0, lo):
                assert hash_op("stream", n) >= target
    asyncio.run(scenario())
