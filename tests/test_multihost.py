"""Pod-as-one-miner (multi-host) end-to-end: 2 local CPU processes.

VERDICT r2 task 7: the north-star deployment shape is a whole multi-host
pod joining the scheduler as ONE miner — host 0 owns the LSP client, every
host executes the same sharded search over the GLOBAL mesh, chunk bounds
ride one tiny pod broadcast per Request (parallel/multihost.py).

Here the "pod" is 2 local processes x 2 virtual CPU devices each (4 global
devices) glued by ``jax.distributed`` over localhost; the scheduler +
server run as a third OS process and a stock CLI client submits the job.
Exactly one miner must Join (host 0), and the Result must be bit-identical
to the oracle.
"""

import os
import socket
import subprocess
import sys
import time

import pytest

from _env_detect import SKIP_REASON, tpu_plugin_without_device
from distributed_bitcoinminer_tpu.bitcoin.hash import scan_min

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# Both tests spawn jax.distributed children that run backend discovery
# WITHOUT the suite's JAX_PLATFORMS=cpu config pin; on a chip-less box
# carrying the libtpu plugin those children wedge in TPU/GCP-metadata
# init until their deadlines kill them (the recorded pre-existing
# environmental failures — see tests/_env_detect.py).
pytestmark = pytest.mark.skipif(tpu_plugin_without_device(),
                                reason=SKIP_REASON)


def _free_udp_port():
    with socket.socket(socket.AF_INET, socket.SOCK_DGRAM) as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _free_tcp_port():
    with socket.socket(socket.AF_INET, socket.SOCK_STREAM) as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _spawn(args, extra_env, log_path=None):
    """Long-lived children write to a file, not a PIPE nobody drains (a
    full 64K pipe buffer would block the child mid-write)."""
    env = dict(os.environ, PYTHONPATH=_REPO)
    env.update(extra_env)
    if log_path is not None:
        log = open(log_path, "w")
        return subprocess.Popen(
            [sys.executable, "-m", *args], cwd=_REPO, env=env,
            stdout=log, stderr=subprocess.STDOUT, text=True)
    return subprocess.Popen(
        [sys.executable, "-m", *args], cwd=_REPO, env=env,
        stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True)


def test_follower_death_mid_job_bounded_and_chunk_reexecutes(tmp_path):
    """VERDICT r3 task 7: kill the follower mid-job. The owner's wedged
    collective must be BOUNDED (bounded_pod_call: DBM_POD_TIMEOUT_S then
    process exit), the scheduler must declare the pod-miner lost and
    re-execute its chunk on the surviving plain miner, and the client must
    still receive the bit-exact Result (recovery contract:
    ref bitcoin/server/server.go:326-376)."""
    lsp_port = _free_udp_port()
    coord_port = _free_tcp_port()
    pkg = "distributed_bitcoinminer_tpu.apps"
    lsp_env = {"DBM_EPOCH_MILLIS": "200", "DBM_EPOCH_LIMIT": "60",
               "DBM_WINDOW": "5", "JAX_PLATFORMS": "cpu"}
    pod_env = {
        **lsp_env,
        "XLA_FLAGS": "--xla_force_host_platform_device_count=2",
        "DBM_COORDINATOR": f"127.0.0.1:{coord_port}",
        "DBM_NUM_PROCS": "2",
        # Tiny batch = the pod grinds its job slowly, guaranteeing the
        # kill lands mid-job; the bound then fires well inside the test.
        "DBM_BATCH": "64",
        "DBM_POD_TIMEOUT_S": "20",
    }
    server = _spawn([f"{pkg}.server", str(lsp_port)], lsp_env,
                    log_path=tmp_path / "server.log")
    owner = follower = spare = client = None
    try:
        time.sleep(1.0)
        owner = _spawn([f"{pkg}.miner", f"127.0.0.1:{lsp_port}"],
                       {**pod_env, "DBM_PROC_ID": "0"},
                       log_path=tmp_path / "owner.log")
        follower = _spawn([f"{pkg}.miner", f"127.0.0.1:{lsp_port}"],
                          {**pod_env, "DBM_PROC_ID": "1"},
                          log_path=tmp_path / "follower.log")
        # Submit FIRST: the request queues until the pod joins, so the
        # pod — the only miner — owns the whole job when the kill lands
        # (spawning a spare up front raced the slow pod join and handed
        # the spare the entire range, leaving the pod idle and unbounded).
        client = _spawn(
            [f"{pkg}.client", f"127.0.0.1:{lsp_port}", "drill", "1999999"],
            lsp_env)
        time.sleep(12.0)  # pod init + join + job broadcast + collective
        follower.kill()
        follower.wait()
        # NOW the rescue miner joins; it inherits the chunk once the
        # owner's bound fires and the scheduler declares the pod lost.
        spare = _spawn([f"{pkg}.miner", f"127.0.0.1:{lsp_port}"],
                       {**lsp_env, "DBM_COMPUTE": "host"},
                       log_path=tmp_path / "spare.log")
        out, err = client.communicate(timeout=240)
        want_hash, want_nonce = scan_min("drill", 0, 2000000)  # +1 ref quirk
        assert out.strip() == f"Result {want_hash} {want_nonce}", (
            out, err, (tmp_path / "owner.log").read_text()[-800:])
        # The owner must have EXITED — wait() raises TimeoutExpired if it
        # is still alive and wedged — and specifically through
        # bounded_pod_call's hard exit (17): the distributed runtime's own
        # heartbeat failure path takes ~100 s, well past this 20 s bound,
        # so the bound must be what fired.
        try:
            rc = owner.wait(timeout=60)
        except subprocess.TimeoutExpired:
            raise AssertionError("owner still alive (wedged) — the pod "
                                 "timeout bound never fired")
        assert rc == 17, (rc, (tmp_path / "owner.log").read_text()[-800:])
    finally:
        for proc in (client, spare, follower, owner, server):
            if proc is not None:
                proc.kill()
                proc.wait()


def test_pod_joins_as_one_miner_and_matches_oracle(tmp_path):
    lsp_port = _free_udp_port()
    coord_port = _free_tcp_port()
    pkg = "distributed_bitcoinminer_tpu.apps"
    pod_env = {
        "JAX_PLATFORMS": "cpu",
        "XLA_FLAGS": "--xla_force_host_platform_device_count=2",
        "DBM_COORDINATOR": f"127.0.0.1:{coord_port}",
        "DBM_NUM_PROCS": "2",
        "DBM_BATCH": "64",
        # Fast transport so the pod's compile pauses can't trip epochs.
        "DBM_EPOCH_MILLIS": "200", "DBM_EPOCH_LIMIT": "60",
        "DBM_WINDOW": "5",
    }
    lsp_env = {"DBM_EPOCH_MILLIS": "200", "DBM_EPOCH_LIMIT": "60",
               "DBM_WINDOW": "5", "JAX_PLATFORMS": "cpu"}
    server = _spawn([f"{pkg}.server", str(lsp_port)], lsp_env,
                    log_path=tmp_path / "server.log")
    owner = follower = client = client2 = None
    try:
        time.sleep(1.0)
        owner = _spawn([f"{pkg}.miner", f"127.0.0.1:{lsp_port}"],
                       {**pod_env, "DBM_PROC_ID": "0"},
                       log_path=tmp_path / "owner.log")
        follower = _spawn([f"{pkg}.miner", f"127.0.0.1:{lsp_port}"],
                          {**pod_env, "DBM_PROC_ID": "1"},
                          log_path=tmp_path / "follower.log")
        time.sleep(2.0)  # distributed init + LSP join
        client = _spawn(
            [f"{pkg}.client", f"127.0.0.1:{lsp_port}", "podjob", "20000"],
            lsp_env)
        # 90s covers pod init + first-job compiles with several-x margin
        # (the steady-state second leg below completes in seconds); on a
        # box whose multi-process jax.distributed cannot init at all the
        # full deadline is burned, so a tighter bound keeps the tier-1
        # suite inside its wall budget there.
        out, err = client.communicate(timeout=90)
        want_hash, want_nonce = scan_min("podjob", 0, 20001)  # +1 ref quirk
        assert out.strip() == f"Result {want_hash} {want_nonce}", (out, err)

        # Difficulty job through the SAME live pod (VERDICT r3 weak #4
        # tail): the target broadcasts as opcode 2, every host runs the
        # lockstep search_until, and the Result is the first-qualifying
        # nonce exactly as the host oracle sees it.
        from distributed_bitcoinminer_tpu.bitcoin.hash import scan_until
        target = 1 << 58
        u_want = scan_until("podjob", 0, 20001, target)
        assert u_want[2], "test target must be reachable in the range"
        client2 = _spawn(
            [f"{pkg}.client", f"127.0.0.1:{lsp_port}", "podjob", "20000",
             str(target)],
            lsp_env)
        out2, err2 = client2.communicate(timeout=180)
        assert out2.strip() == f"Result {u_want[0]} {u_want[1]}", (
            out2, err2, (tmp_path / "owner.log").read_text()[-800:])

        # The pod joined as ONE miner: kill the server; the owner's LSP
        # connection dies, it broadcasts stop, and BOTH pod processes exit
        # cleanly on their own.
        server.kill()
        server.wait()
        assert owner.wait(timeout=60) == 0, \
            (tmp_path / "owner.log").read_text()[-800:]
        assert follower.wait(timeout=60) == 0, \
            (tmp_path / "follower.log").read_text()[-800:]
    finally:
        for proc in (client2, client, follower, owner, server):
            if proc is not None:
                proc.kill()
                proc.wait()
