"""Pod-as-one-miner (multi-host) end-to-end: 2 local CPU processes.

VERDICT r2 task 7: the north-star deployment shape is a whole multi-host
pod joining the scheduler as ONE miner — host 0 owns the LSP client, every
host executes the same sharded search over the GLOBAL mesh, chunk bounds
ride one tiny pod broadcast per Request (parallel/multihost.py).

Here the "pod" is 2 local processes x 2 virtual CPU devices each (4 global
devices) glued by ``jax.distributed`` over localhost; the scheduler +
server run as a third OS process and a stock CLI client submits the job.
Exactly one miner must Join (host 0), and the Result must be bit-identical
to the oracle.
"""

import os
import socket
import subprocess
import sys
import time

from distributed_bitcoinminer_tpu.bitcoin.hash import scan_min

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _free_udp_port():
    with socket.socket(socket.AF_INET, socket.SOCK_DGRAM) as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _free_tcp_port():
    with socket.socket(socket.AF_INET, socket.SOCK_STREAM) as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _spawn(args, extra_env, log_path=None):
    """Long-lived children write to a file, not a PIPE nobody drains (a
    full 64K pipe buffer would block the child mid-write)."""
    env = dict(os.environ, PYTHONPATH=_REPO)
    env.update(extra_env)
    if log_path is not None:
        log = open(log_path, "w")
        return subprocess.Popen(
            [sys.executable, "-m", *args], cwd=_REPO, env=env,
            stdout=log, stderr=subprocess.STDOUT, text=True)
    return subprocess.Popen(
        [sys.executable, "-m", *args], cwd=_REPO, env=env,
        stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True)


def test_pod_joins_as_one_miner_and_matches_oracle(tmp_path):
    lsp_port = _free_udp_port()
    coord_port = _free_tcp_port()
    pkg = "distributed_bitcoinminer_tpu.apps"
    pod_env = {
        "JAX_PLATFORMS": "cpu",
        "XLA_FLAGS": "--xla_force_host_platform_device_count=2",
        "DBM_COORDINATOR": f"127.0.0.1:{coord_port}",
        "DBM_NUM_PROCS": "2",
        "DBM_BATCH": "64",
        # Fast transport so the pod's compile pauses can't trip epochs.
        "DBM_EPOCH_MILLIS": "200", "DBM_EPOCH_LIMIT": "60",
        "DBM_WINDOW": "5",
    }
    server = _spawn([f"{pkg}.server", str(lsp_port)],
                    {"DBM_EPOCH_MILLIS": "200", "DBM_EPOCH_LIMIT": "60",
                     "DBM_WINDOW": "5", "JAX_PLATFORMS": "cpu"},
                    log_path=tmp_path / "server.log")
    owner = follower = client = None
    try:
        time.sleep(1.0)
        owner = _spawn([f"{pkg}.miner", f"127.0.0.1:{lsp_port}"],
                       {**pod_env, "DBM_PROC_ID": "0"},
                       log_path=tmp_path / "owner.log")
        follower = _spawn([f"{pkg}.miner", f"127.0.0.1:{lsp_port}"],
                          {**pod_env, "DBM_PROC_ID": "1"},
                          log_path=tmp_path / "follower.log")
        time.sleep(2.0)  # distributed init + LSP join
        client = _spawn(
            [f"{pkg}.client", f"127.0.0.1:{lsp_port}", "podjob", "20000"],
            {"DBM_EPOCH_MILLIS": "200", "DBM_EPOCH_LIMIT": "60",
             "DBM_WINDOW": "5", "JAX_PLATFORMS": "cpu"})
        out, err = client.communicate(timeout=180)
        want_hash, want_nonce = scan_min("podjob", 0, 20001)  # +1 ref quirk
        assert out.strip() == f"Result {want_hash} {want_nonce}", (out, err)

        # The pod joined as ONE miner: kill the server; the owner's LSP
        # connection dies, it broadcasts stop, and BOTH pod processes exit
        # cleanly on their own.
        server.kill()
        server.wait()
        assert owner.wait(timeout=60) == 0, \
            (tmp_path / "owner.log").read_text()[-800:]
        assert follower.wait(timeout=60) == 0, \
            (tmp_path / "follower.log").read_text()[-800:]
    finally:
        for proc in (client, follower, owner, server):
            if proc is not None:
                proc.kill()
                proc.wait()
