"""Fair-share QoS dispatch plane (ISSUE 5): DRR fairness, admission,
shedding, FIFO parity, and the chunked-grant merge invariants.

Three layers, mirroring the suite layout the scheduler already has:

- **Plane units** — ``apps/qos.py`` in isolation: token-bucket math with a
  fake clock, the DRR grant-share-∝-weight invariant, the no-starvation
  bound, tenant GC / metric-series retirement.
- **Scripted scheduler** — the synchronous FakeServer drive of
  test_scheduler_recovery.py: chunk-granular interleaving (a mouse lands
  mid-elephant), weighted shares under a two-elephant storm, admission
  and overload shedding, cache-replay quota bypass, per-tenant queue-age
  alarms, and the DBM_QOS=0 bit-for-bit FIFO parity pin the tier-1
  knob-off matrix leg runs.
- **End-to-end** — real localhost LSP: shed → ``submit_with_retry``
  backoff → resubmit round-trip, and a seeded elephant+mice storm with a
  wedged miner mid-storm asserting exactly-once merges and oracle-exact
  answers.
"""

from __future__ import annotations

import asyncio
import time

import pytest

from distributed_bitcoinminer_tpu.apps.client import submit_with_retry
from distributed_bitcoinminer_tpu.apps.qos import QosPlane, TokenBucket
from distributed_bitcoinminer_tpu.apps.scheduler import Scheduler
from distributed_bitcoinminer_tpu.bitcoin.hash import scan_min
from distributed_bitcoinminer_tpu.bitcoin.message import (Message, MsgType,
                                                          new_request,
                                                          new_result)
from distributed_bitcoinminer_tpu.lsp.params import Params
from distributed_bitcoinminer_tpu.lsp.server import new_async_server
from distributed_bitcoinminer_tpu.lspnet import chaos
from distributed_bitcoinminer_tpu.utils.config import (LeaseParams,
                                                       QosParams,
                                                       RetryParams,
                                                       VerifyParams)
from distributed_bitcoinminer_tpu.utils.metrics import Registry

MINER_A, MINER_B, MINER_C = 1, 2, 3
TEN_X, TEN_Y, TEN_Z = 10, 11, 12


@pytest.fixture(autouse=True)
def _sanitize_armed(monkeypatch):
    """ISSUE 7: this suite runs with the runtime sanitizer armed — its
    concurrent chunked dispatch and shed/cancel paths are exactly what
    the loop-stall watchdog and thread-ownership assertions sweep.
    Violations warn and count, never fail a test; the watchdog is
    uninstalled afterwards so timing-sensitive suites see stock
    callbacks.

    ISSUE 10: the flight recorder rides along (DBM_TRACE=1, overriding
    a matrix leg's DBM_TRACE=0 for THIS suite's shed/grant storms) so
    the QoS paths run with ring recording + dump triggers armed —
    dumps are log lines, never failures."""
    from distributed_bitcoinminer_tpu.utils import sanitize, trace
    monkeypatch.setenv("DBM_SANITIZE", "1")
    monkeypatch.setenv("DBM_TRACE", "1")
    trace.ensure_tracer()
    yield
    sanitize.uninstall_watchdog()


# --------------------------------------------------------------- plane units


class FakeClock:
    def __init__(self, t=100.0):
        self.t = t

    def __call__(self):
        return self.t


def test_token_bucket_spend_and_refill():
    clk = FakeClock()
    b = TokenBucket(rate=2.0, burst=3.0, clock=clk)
    assert b.take() and b.take() and b.take()
    assert not b.take()            # drained, no time passed
    clk.t += 0.5                   # 1 token refilled
    assert b.take()
    assert not b.take()
    clk.t += 10.0                  # refill clamps at burst
    assert b.level == pytest.approx(3.0)
    # rate<=0 disables admission: always grants, always full.
    off = TokenBucket(rate=0.0, burst=1.0, clock=clk)
    for _ in range(100):
        assert off.take()
    assert off.full


def drive_drr(plane: QosPlane, weights: dict, cost: int, grants: int):
    """Constant-backlog DRR drive: every tenant always has a next item of
    ``cost`` nonces; run ``grants`` picks and return per-tenant counts."""
    for t, w in weights.items():
        plane.tenant(t, weight=w)
    counts = {t: 0 for t in weights}
    for _ in range(grants):
        t = plane.pick({t: cost for t in weights})
        plane.on_grant(t, cost)
        plane.on_chunk_answered(t)
        counts[t] += 1
    return counts


def test_drr_grant_share_proportional_to_weight():
    """The ISSUE invariant: sustained grant share converges to the weight
    ratio, at CHUNK granularity (every grant here is one equal-cost
    chunk)."""
    plane = QosPlane(Registry())
    weights = {TEN_X: 1.0, TEN_Y: 2.0, TEN_Z: 4.0}
    counts = drive_drr(plane, weights, cost=100, grants=700)
    total_w = sum(weights.values())
    for t, w in weights.items():
        assert counts[t] / 700 == pytest.approx(w / total_w, abs=0.05), \
            (t, counts)
    # The metric gauges mirror the same shares.
    assert plane.grant_share(TEN_Z) == pytest.approx(4 / 7, abs=0.05)


def test_drr_no_starvation_bound():
    """Every backlogged tenant is granted within ~ceil(1/weight) ring
    passes: even a weight-0.1 mouse among heavy elephants is granted
    within a bounded window, never starved."""
    plane = QosPlane(Registry())
    weights = {TEN_X: 10.0, TEN_Y: 10.0, TEN_Z: 0.1}
    counts = drive_drr(plane, weights, cost=50, grants=600)
    assert counts[TEN_Z] >= 2          # granted, repeatedly
    # And the heavies split the rest roughly evenly between them.
    assert counts[TEN_X] == pytest.approx(counts[TEN_Y], rel=0.2)


def test_plane_forget_and_gc_retire_series():
    reg = Registry()
    plane = QosPlane(reg)
    plane.tenant(TEN_X, weight=1.0)
    plane.on_grant(TEN_X, 100)
    assert "qos_granted_chunks{tenant=10}" in str(reg.snapshot())
    plane.on_chunk_answered(TEN_X)
    plane.gc(busy=set())               # idle, bucket full -> forgotten
    snap = str(reg.snapshot())
    assert "tenant=10" not in snap
    assert plane.tenants == {}
    # A busy tenant survives the same sweep.
    plane.tenant(TEN_Y, weight=1.0)
    plane.gc(busy={TEN_Y})
    assert TEN_Y in plane.tenants


# --------------------------------------------------- scripted scheduler layer


class FakeServer:
    """Records writes and conn closes; the scheduler never reads it."""

    def __init__(self):
        self.writes = []    # (conn_id, Message)
        self.closed = []

    def write(self, conn_id, payload):
        self.writes.append((conn_id, Message.from_json(payload)))

    def close_conn(self, conn_id):
        self.closed.append(conn_id)

    def sent_to(self, conn_id, mtype=None):
        return [m for c, m in self.writes
                if c == conn_id and (mtype is None or m.type == mtype)]


def make_sched(qos=None, lease=None):
    # pop_next answers with synthetic hashes the claim check would
    # reject; verification has its own suite, so pin it off here.
    server = FakeServer()
    return Scheduler(server, lease=lease or LeaseParams(),
                     qos=qos or QosParams(),
                     verify=VerifyParams(enabled=False)), server


def chunky_qos(**kw):
    """QoS params that chunk anything non-trivial on a warmed pool."""
    kw.setdefault("wholesale_s", 0.5)
    kw.setdefault("chunk_s", 1.0)
    kw.setdefault("depth", 2)
    return QosParams(**kw)


def pin_rate(sched, rate=100.0):
    """Freeze the pool throughput estimate: scripted pops answer in
    microseconds, and the resulting ~1e8 nps EWMA would collapse every
    later chunk plan to one giant chunk (a fake-harness artifact, not a
    product behavior — real miners report honest elapsed times)."""
    sched._pool_rate = rate
    sched._observe_result = lambda miner, chunk: None


def pop_next(sched):
    """Answer the oldest pending chunk of the first busy miner, returning
    ``(data, idx)`` — hash encodes the chunk's lower bound so arg-min
    merges resolve to each request's first chunk deterministically."""
    for m in sched.miners:
        if m.pending:
            c = m.pending[0]
            sched._on_result(m.conn_id,
                             new_result(1_000_000 + c.lower, c.lower))
            return c.data, c.idx
    return None


def test_mouse_interleaves_mid_elephant():
    """The tentpole no-starvation shape: a mouse submitted after a chunked
    elephant is granted as soon as a live-FIFO slot frees — within a few
    chunk pops — instead of waiting for the elephant's last merge."""
    sched, server = make_sched(qos=chunky_qos())
    sched._on_join(MINER_A)
    sched._on_join(MINER_B)
    pin_rate(sched)
    sched._on_request(TEN_X, new_request("elephant", 0, 9999))
    assert sched.current.qos_mode == "chunked"
    assert sched.current.num_chunks > 10
    sched._on_request(TEN_Y, new_request("mouse", 0, 49))
    pops = []
    for _ in range(300):
        got = pop_next(sched)
        if got is None:
            break
        pops.append(got)
    mouse_at = [i for i, (d, _) in enumerate(pops) if d == "mouse"]
    assert mouse_at and mouse_at[0] <= 6, pops[:10]
    # Both merges exact: each request's reply is its own chunk-0 arg-min.
    assert [(m.hash, m.nonce) for m in server.sent_to(TEN_Y,
                                                      MsgType.RESULT)] \
        == [(1_000_000, 0)]
    assert [(m.hash, m.nonce) for m in server.sent_to(TEN_X,
                                                      MsgType.RESULT)] \
        == [(1_000_000, 0)]
    assert sched.stats["dup_results"] == 0


def test_weighted_share_between_concurrent_elephants():
    """Two chunked elephants, weights 1 vs 3: granted chunks converge to
    the weight ratio while both are backlogged."""
    sched, _ = make_sched(qos=chunky_qos(weights=((str(TEN_X), 1.0),
                                                  (str(TEN_Y), 3.0))))
    sched._on_join(MINER_A)
    sched._on_join(MINER_B)
    pin_rate(sched)
    sched._on_request(TEN_X, new_request("el-x", 0, 9999))
    sched._on_request(TEN_Y, new_request("el-y", 0, 9999))
    granted = {"el-x": 0, "el-y": 0}
    for _ in range(80):                 # stay inside both backlogs
        data, _idx = pop_next(sched)
        granted[data] += 1
    assert granted["el-y"] / granted["el-x"] == pytest.approx(3.0, rel=0.35)


def test_qos_off_matches_stock_fifo_bit_for_bit():
    """The acceptance pin (run under DBM_QOS=0 in the tier-1 matrix leg
    too): with the plane disabled, every write the scheduler emits — conn,
    type, bounds, order — is identical to the stock FIFO scheduler's, for
    a multi-tenant backlog with interleaved results."""
    def drive(sched):
        sched._on_join(MINER_A)
        sched._on_join(MINER_B)
        sched._on_request(TEN_X, new_request("alpha", 0, 999))
        sched._on_request(TEN_Y, new_request("beta", 0, 499))
        sched._on_request(TEN_X, new_request("gamma", 0, 99))
        while pop_next(sched) is not None:
            pass

    stock, stock_srv = make_sched(qos=QosParams(enabled=False))
    # Give the off-plane scheduler a warmed pool too: enabled=False must
    # pin the stock path regardless of throughput state.
    stock._pool_rate = 100.0
    drive(stock)
    off, off_srv = make_sched(qos=QosParams(enabled=False, wholesale_s=0.1,
                                            chunk_s=0.5))
    off._pool_rate = 100.0
    drive(off)
    assert [(c, m.to_json()) for c, m in off_srv.writes] == \
        [(c, m.to_json()) for c, m in stock_srv.writes]


def test_qos_on_cold_pool_single_tenant_matches_fifo():
    """Default-on safety: a cold pool (no throughput EWMA) dispatches
    wholesale through the stock path, so single-tenant traffic is
    bit-identical with the plane enabled."""
    def drive(sched):
        sched._on_join(MINER_A)
        sched._on_join(MINER_B)
        for mx in (999, 499, 99):
            sched._on_request(TEN_X, new_request(f"r{mx}", 0, mx))
        while pop_next(sched) is not None:
            pass

    on, on_srv = make_sched(qos=QosParams())          # enabled, cold pool
    drive(on)
    off, off_srv = make_sched(qos=QosParams(enabled=False))
    drive(off)
    assert [(c, m.to_json()) for c, m in on_srv.writes] == \
        [(c, m.to_json()) for c, m in off_srv.writes]


def test_chunked_answers_bit_exact_vs_fifo():
    """Result parity: the chunked grant path merges to the same
    (hash, nonce) the stock wholesale path produces, pinned with REAL
    hashes over a small range (every chunk answered with its true
    arg-min, like a pool of honest miners)."""
    data, max_nonce = "parity", 799
    want = scan_min(data, 0, max_nonce + 1)      # reference bound quirk

    def drive(sched):
        sched._on_join(MINER_A)
        sched._on_join(MINER_B)
        sched._pool_rate = 50.0                  # warm -> chunked when on
        sched._on_request(TEN_X, new_request(data, 0, max_nonce))
        for _ in range(200):
            advanced = False
            for m in sched.miners:
                if m.pending:
                    c = m.pending[0]
                    h, n = scan_min(data, c.lower, c.upper)
                    sched._on_result(m.conn_id, new_result(h, n))
                    advanced = True
                    break
            if not advanced:
                break

    on, on_srv = make_sched(qos=chunky_qos())
    drive(on)
    assert on.stats["qos_grants"] > 2            # really took the chunk path
    off, off_srv = make_sched(qos=QosParams(enabled=False))
    drive(off)
    got_on = [(m.hash, m.nonce)
              for m in on_srv.sent_to(TEN_X, MsgType.RESULT)]
    got_off = [(m.hash, m.nonce)
               for m in off_srv.sent_to(TEN_X, MsgType.RESULT)]
    assert got_on == got_off == [want]


def test_admission_sheds_and_closes_conn():
    sched, server = make_sched(
        qos=chunky_qos(rate=0.0001, burst=1.0))
    sched._on_join(MINER_A)
    sched._on_request(TEN_X, new_request("first", 0, 99))    # takes the token
    sched._on_request(TEN_X, new_request("second", 0, 99))   # bucket empty
    assert sched.stats["qos_shed"] == 1
    assert server.closed == [TEN_X]
    assert sched.metrics.counter("qos_shed_reason",
                                 reason="admission").value == 1
    # The first request is unaffected and completes.
    pop_next(sched)
    assert len(server.sent_to(TEN_X, MsgType.RESULT)) == 1


def test_overload_sheds_oldest_queued():
    """DBM_QOS_MAX_QUEUED: intake above the bound cancels the OLDEST
    queued request through the trace/cancel path and closes its conn."""
    sched, server = make_sched(qos=chunky_qos(max_queued=2))
    # No miners: everything queues.
    sched._on_request(TEN_X, new_request("oldest", 0, 99))
    sched._on_request(TEN_Y, new_request("mid", 0, 99))
    sched._on_request(TEN_Z, new_request("newest", 0, 99))
    assert [r.data for r in sched.queue] == ["mid", "newest"]
    assert sched.stats["qos_shed"] == 1
    assert server.closed == [TEN_X]
    assert sched.metrics.counter("qos_shed_reason",
                                 reason="overload").value == 1
    # The shed request's trace records the cancellation.
    shed_trace = sched.trace("shed:1")
    assert shed_trace is not None
    assert any(e.get("event") == "cancel" and e.get("reason") == "shed"
               for e in shed_trace.to_dict()["events"])


def test_cache_replay_bypasses_admission_quota():
    """ISSUE satellite: a retry storm of already-answered requests burns
    no tokens and is never shed — replays answer before admission."""
    sched, server = make_sched(qos=chunky_qos(rate=0.0001, burst=1.0))
    sched._on_join(MINER_A)
    sched._on_request(TEN_X, new_request("memo", 0, 99))     # the one token
    pop_next(sched)                                          # answer + store
    for _ in range(5):                                       # retry storm
        sched._on_request(TEN_X, new_request("memo", 0, 99))
    assert sched.stats["qos_shed"] == 0
    assert server.closed == []
    assert sched.stats["cache_hits"] == 5
    assert len(server.sent_to(TEN_X, MsgType.RESULT)) == 6


def test_inflight_cap_limits_tenant_grants():
    """DBM_QOS_MAX_INFLIGHT bounds one tenant's granted-but-unanswered
    chunks even with pool capacity to spare."""
    sched, _ = make_sched(qos=chunky_qos(max_inflight=2, depth=8))
    sched._on_join(MINER_A)
    sched._on_join(MINER_B)
    pin_rate(sched)
    sched._on_request(TEN_X, new_request("capped", 0, 9999))
    assert sched.current.granted_chunks == 2      # cap, not depth*miners
    pop_next(sched)
    assert sched.current.granted_chunks == 3      # one answered, one more


def test_difficulty_prefix_release_skips_ungranted_chunks():
    """A chunked difficulty elephant whose chunk 0 hits releases
    immediately; UNGRANTED chunks evaporate (their scans are skipped) and
    late results for granted ones pop as stale — exactly-once semantics
    under the early release."""
    sched, server = make_sched(qos=chunky_qos())
    sched._on_join(MINER_A)
    sched._on_join(MINER_B)
    pin_rate(sched)
    sched._on_request(TEN_X, new_request("diff", 0, 9999, target=500))
    req = sched.current
    assert req.qos_mode == "chunked"
    granted = req.granted_chunks
    assert granted < req.num_chunks
    # Chunk 0 reports a qualifying hit (hash < target): prefix release.
    c = sched.miners[0].pending[0]
    assert c.idx == 0
    sched._on_result(MINER_A, new_result(7, 3, target=500))
    replies = server.sent_to(TEN_X, MsgType.RESULT)
    assert [(m.hash, m.nonce) for m in replies] == [(7, 3)]
    assert sched.current is None
    # No further grants happen for the retired job; the still-pending
    # granted chunk pops as stale without a second reply.
    assert sched.stats["qos_grants"] == granted
    while pop_next(sched) is not None:
        pass
    assert len(server.sent_to(TEN_X, MsgType.RESULT)) == 1


def test_per_tenant_queue_age_alarm_carries_grant_share():
    """ISSUE satellite: the sweep alarms on the OLDEST queued request per
    tenant (not every over-age request) and stamps the tenant's grant
    share into the trace, so a starved mouse reads differently from a
    busy elephant."""
    sched, _ = make_sched(qos=chunky_qos(),
                          lease=LeaseParams(queue_alarm_s=0.05))
    # No miners: requests sit queued. Two tenants, three requests.
    sched._on_request(TEN_X, new_request("x-old", 0, 99))
    sched._on_request(TEN_X, new_request("x-new", 0, 99))
    sched._on_request(TEN_Y, new_request("y-old", 0, 99))
    for r in sched.queue:
        r.queued_at -= 1.0              # age everything past the bound
    sched._check_queue_age()
    assert sched.stats["queue_alarms"] == 2      # one per TENANT
    alarmed = [r for r in sched.queue if r.last_alarm]
    assert sorted(r.data for r in alarmed) == ["x-old", "y-old"]
    ev = [e for e in alarmed[0].trace.to_dict()["events"]
          if e.get("event") == "queue_alarm"]
    assert ev and "grant_share" in ev[0] and "tenant" in ev[0]


def test_idle_tenant_gc_rides_sweep_state():
    """The sweep's GC forgets only idle tenants (nothing queued, nothing
    in flight, bucket full) and drops their metric series."""
    sched, _ = make_sched(qos=chunky_qos())
    sched._on_join(MINER_A)
    sched._on_request(TEN_X, new_request("done", 0, 99))
    pop_next(sched)                     # TEN_X now idle
    sched._on_request(TEN_Y, new_request("busy", 0, 99))
    assert TEN_X in sched.qos_plane.tenants
    sched.qos_plane.gc({r.conn_id for r in sched.queue}
                       | {r.conn_id for r in sched.inflight.values()})
    assert TEN_X not in sched.qos_plane.tenants
    assert TEN_Y in sched.qos_plane.tenants


# ------------------------------------------------------------- e2e: real LSP


def qos_params_net(epoch_ms=40, limit=8, window=8):
    return Params(epoch_limit=limit, epoch_millis=epoch_ms,
                  window_size=window, max_backoff_interval=2)


def test_shed_resubmit_roundtrip_through_submit_with_retry():
    """The shedding contract end-to-end: an overload-shed request's conn
    closes, its ``submit_with_retry`` client backs off and resubmits, and
    the resubmission is served once the queue drains — backoff latency,
    never a hang into the wire deadline."""
    params = qos_params_net()

    async def scenario():
        server = await new_async_server(0, params)
        sched = Scheduler(server, lease=LeaseParams(),
                          qos=QosParams(max_queued=1))
        sched_task = asyncio.create_task(sched.run())
        hostport = f"127.0.0.1:{server.port}"
        try:
            # No miners yet: the victim's request queues, then a second
            # tenant's request overflows max_queued=1 and sheds it.
            retry = RetryParams(attempts=6, timeout_s=4.0, backoff_s=0.2,
                                backoff_cap_s=0.5)
            victim = asyncio.create_task(submit_with_retry(
                hostport, "victim", 299, 0, params, retry))
            for _ in range(200):
                if sched.queue:
                    break
                await asyncio.sleep(0.01)
            sheds_before = sched.stats["qos_shed"]
            sched._on_request(TEN_Y, new_request("flood", 0, 99))
            assert sched.stats["qos_shed"] == sheds_before + 1
            # Now let the pool serve: the victim's backed-off resubmit
            # (and the flood request) complete.
            m = chaos.ChaosMiner(hostport, params=params,
                                 searcher_factory=lambda d, b:
                                 _Oracle(d), name="m1")
            await m.start()
            try:
                got = await asyncio.wait_for(victim, 30)
            finally:
                await m.close()
            want = scan_min("victim", 0, 300)
            assert got is not None and got[:2] == want, (got, want)
        finally:
            sched_task.cancel()
            await server.close()

    asyncio.run(scenario())


class _Oracle:
    def __init__(self, data, delay=0.0):
        self.data = data
        self.delay = delay

    def search(self, lower, upper):
        if self.delay:
            time.sleep(self.delay)
        return scan_min(self.data, lower, upper)


@pytest.mark.parametrize("seed", [11, 23])
def test_chaos_storm_wedged_miner_exactly_once(seed):
    """Chaos leg (ISSUE satellite): an elephant + mice storm through a
    real LSP stack in CHUNKED mode, with one miner wedging mid-storm:
    leases blow, chunks re-issue, and every request still merges exactly
    once with the oracle-exact answer."""
    import random
    rng = random.Random(seed)
    params = qos_params_net()
    lease = LeaseParams(grace_s=0.8, factor=6.0, floor_s=0.4, tick_s=0.05,
                        quarantine_after=4, ewma_alpha=0.5)
    # Chunk aggressively so the elephant really exercises the grant loop:
    # ~0.05s chunks against the oracle's per-chunk delay.
    qos = QosParams(wholesale_s=0.2, chunk_s=0.05, depth=2, max_chunks=64)

    async def scenario():
        server = await new_async_server(0, params)
        sched = Scheduler(server, lease=lease, qos=qos)
        sched_task = asyncio.create_task(sched.run())
        hostport = f"127.0.0.1:{server.port}"
        miners = []
        try:
            for name in ("m1", "m2", "wedgy"):
                m = chaos.ChaosMiner(
                    hostport, params=params,
                    searcher_factory=lambda d, b: _Oracle(d, delay=0.02),
                    name=name)
                await m.start()
                miners.append(m)
            for _ in range(200):
                if len(sched.miners) == 3:
                    break
                await asyncio.sleep(0.01)
            # Warm the pool (cold pools dispatch wholesale by design).
            from distributed_bitcoinminer_tpu.apps.client import submit
            warm = await asyncio.wait_for(
                submit(hostport, "warm", 2999, params), 20)
            assert warm == scan_min("warm", 0, 3000)
            # The windowed rate sampler needs RATE_WINDOW_S of observed
            # wall clock before it publishes a pool rate; one tiny warm
            # request can't fill that, so pin the rates directly (the
            # file-wide idiom) — ~20k-nonce elephant / 1000-nonce chunks
            # at chunk_s=0.05 forces a real multi-grant chunked run.
            sched._pool_rate = 20_000.0
            for m in sched.miners:
                m.rate_ewma = 20_000.0

            elephant_max = 20_000 + rng.randrange(5_000)
            mice_max = [200 + rng.randrange(300) for _ in range(3)]
            tasks = [asyncio.create_task(asyncio.wait_for(
                submit(hostport, "elephant", elephant_max, params), 60))]
            await asyncio.sleep(0.05)       # elephant activates first
            miners[2].wedge()               # wedge mid-storm
            for i, mx in enumerate(mice_max):
                tasks.append(asyncio.create_task(asyncio.wait_for(
                    submit(hostport, f"mouse{i}", mx, params), 60)))
            got = await asyncio.gather(*tasks)
            assert got[0] == scan_min("elephant", 0, elephant_max + 1)
            for i, mx in enumerate(mice_max):
                assert got[1 + i] == scan_min(f"mouse{i}", 0, mx + 1), i
            # Exactly-once: one reply per request, and the storm really
            # ran chunked (the elephant alone needs several grants).
            assert sched.stats["results_sent"] == 1 + 1 + len(mice_max)
            assert sched.stats["qos_grants"] > 4
            miners[2].unwedge()
        finally:
            for m in miners:
                await m.close()
            sched_task.cancel()
            await server.close()

    asyncio.run(scenario())


# ------------------------------------------------- lazy DRR walk (ISSUE 12)


def test_pick_lazy_share_proportional_to_weight():
    """The lazy ring walk preserves the DRR share invariant: with a
    constant backlog and an incremental quantum, sustained grant share
    still converges to the weight ratio."""
    plane = QosPlane(Registry())
    weights = {TEN_X: 1.0, TEN_Y: 2.0, TEN_Z: 4.0}
    for t, w in weights.items():
        plane.tenant(t, weight=w)
        plane.backlog_enter(t)
    counts = {t: 0 for t in weights}
    for _ in range(700):
        t = plane.pick_lazy(lambda tenant: 100)
        assert t is not None
        plane.on_grant(t, 100)
        plane.on_chunk_answered(t)
        counts[t] += 1
    total_w = sum(weights.values())
    for t, w in weights.items():
        assert counts[t] / 700 == pytest.approx(w / total_w, abs=0.05), \
            (t, counts)


def test_pick_lazy_removes_idle_and_zeroes_reentry_deficit():
    """LAZY_REMOVE drops a no-backlog tenant from the ring on the spot,
    forfeiting its deficit; re-entry via backlog_enter starts from zero
    (idle-banks-no-credit at both edges, the sync_backlog rule applied
    lazily)."""
    from distributed_bitcoinminer_tpu.apps.qos import LAZY_REMOVE
    plane = QosPlane(Registry())
    for t in (TEN_X, TEN_Y):
        plane.tenant(t)
        plane.backlog_enter(t)
    plane.tenants[TEN_X].deficit = 500.0

    def head(tenant):
        return LAZY_REMOVE if tenant == TEN_X else 50

    got = plane.pick_lazy(head)
    assert got == TEN_Y
    assert TEN_X not in plane._in_ring and list(plane.ring) == [TEN_Y]
    assert plane.tenants[TEN_X].deficit == 0.0
    # Re-entry starts fresh even if deficit was scribbled meanwhile.
    plane.tenants[TEN_X].deficit = 75.0
    plane.backlog_enter(TEN_X)
    assert plane.tenants[TEN_X].deficit == 0.0
    # A continuing member keeps its earned deficit.
    earned = plane.tenants[TEN_Y].deficit
    plane.backlog_enter(TEN_Y)
    assert plane.tenants[TEN_Y].deficit == earned


def test_pick_lazy_incremental_quantum_unblocks_expensive_head():
    """The incremental quantum bound: once an expensive head has been
    priced, the per-cycle top-up is large enough that its tenant is
    granted within ceil(1/weight) cycles — no starvation of big-chunk
    tenants behind cheap ones."""
    plane = QosPlane(Registry())
    plane.tenant(TEN_X, weight=1.0)
    plane.tenant(TEN_Y, weight=1.0)
    plane.backlog_enter(TEN_X)
    plane.backlog_enter(TEN_Y)
    costs = {TEN_X: 10, TEN_Y: 10_000}
    granted = []
    for _ in range(40):
        t = plane.pick_lazy(lambda tenant: costs[tenant])
        assert t is not None
        plane.on_grant(t, costs[t])
        granted.append(t)
        if granted.count(TEN_Y) >= 2:
            break
    assert granted.count(TEN_Y) >= 2, granted


def test_lazy_pump_matches_stock_walk_replies():
    """Knob A/B (DBM_QOS_LAZY): the lazy pump and the stock walk serve
    the same mixed elephant+mice storm to the same replies per tenant
    (grant ORDER may differ; merges and exactly-once may not)."""
    def drive(lazy):
        sched, server = make_sched(
            qos=chunky_qos(lazy=lazy,
                           weights=((str(TEN_X), 1.0),
                                    (str(TEN_Y), 2.0))))
        sched._on_join(MINER_A)
        sched._on_join(MINER_B)
        pin_rate(sched)
        sched._on_request(TEN_X, new_request("el-x", 0, 9999))
        sched._on_request(TEN_Y, new_request("el-y", 0, 7999))
        sched._on_request(TEN_Z, new_request("mouse", 0, 49))
        for _ in range(500):
            if pop_next(sched) is None:
                break
        return {t: [(m.hash, m.nonce)
                    for m in server.sent_to(t, MsgType.RESULT)]
                for t in (TEN_X, TEN_Y, TEN_Z)}

    lazy, stock = drive(True), drive(False)
    assert lazy == stock
    for t in (TEN_X, TEN_Y, TEN_Z):
        assert len(lazy[t]) == 1, (t, lazy)


def test_lazy_pump_grants_via_direct_enqueue_injection():
    """Ring membership must track EVERY enqueue path: a request injected
    via tenant_plane.enqueue (the driver idiom) still enters the lazy
    ring through the backlog hook and is granted."""
    from distributed_bitcoinminer_tpu.apps.scheduler import Request
    sched, server = make_sched(qos=chunky_qos())
    sched._on_join(MINER_A)
    req = Request(conn_id=TEN_X, data="inject", lower=0, upper=49)
    sched.tenant_plane.enqueue(req)
    sched._maybe_dispatch()
    pop_next(sched)
    assert [(m.hash, m.nonce)
            for m in server.sent_to(TEN_X, MsgType.RESULT)] \
        == [(1_000_000, 0)]
