"""ctest/mtest analog: full-scale Part B sanity runs (VERDICT r2 task 9).

The reference ships staff binaries ``ctest``/``mtest`` that sanity-check a
client and a miner against a live server at real scale
(ref: p1/README.md:137-141; linux builds stripped from this checkout).
These tests reproduce that coverage in-process: a scheduler + miner pool
running the NATIVE C++ scan (SHA-NI where available) over a 2^24-nonce
request, validated bit-for-bit against ``native.scan_min_native`` — the
same oracle the staff binaries embody — including a mid-request miner
kill.
"""

import asyncio
import time

from distributed_bitcoinminer_tpu import native
from distributed_bitcoinminer_tpu.apps.client import submit
from distributed_bitcoinminer_tpu.apps.miner import HostSearcher

from tests.test_apps import Cluster, fast_params

N = 1 << 24


def native_factory(delay: float = 0.0):
    class Slow(HostSearcher):
        def search(self, lower, upper):
            if delay:
                time.sleep(delay)
            return super().search(lower, upper)
    return lambda data, batch: Slow(data)


def test_ctest_analog_full_scale_result_vs_native_oracle():
    """Client sanity at 2^24 nonces: 3 native miners, exact Result.

    The system scans [0, maxNonce+1] (exclusive-upper/inclusive-read ref
    quirk), so the oracle scan covers N+1 nonces.
    """
    async def scenario():
        params = fast_params(epoch_ms=100, limit=30, window=5)
        async with Cluster(params) as c:
            for _ in range(3):
                await c.start_miner(factory=native_factory())
            t0 = time.monotonic()
            got = await asyncio.wait_for(
                submit(c.hostport, "ctest", N - 1, params), 120)
            elapsed = time.monotonic() - t0
            assert got == native.scan_min_native("ctest", 0, N)
            # Generous budget: the reference's sanity binaries run a
            # comparable workload interactively on lab machines.
            assert elapsed < 120
    asyncio.run(scenario())


def test_mtest_analog_miner_killed_mid_request_at_scale():
    """Miner sanity at 2^24 nonces: one of three miners dies mid-chunk;
    the reassigned chunk must land and the merged Result stay exact
    (ref recovery path: server.go:326-376)."""
    async def scenario():
        params = fast_params(epoch_ms=60, limit=4, window=5)
        async with Cluster(params) as c:
            victim = await c.start_miner(factory=native_factory(delay=4.0))
            for _ in range(2):
                await c.start_miner(factory=native_factory())
            pending = asyncio.create_task(
                submit(c.hostport, "mtest", N - 1, params))
            await asyncio.sleep(0.5)   # all three hold chunks; victim naps
            victim.client._conn.abort()
            victim.client._ep.close()
            got = await asyncio.wait_for(pending, 120)
            assert got == native.scan_min_native("mtest", 0, N)
    asyncio.run(scenario())
