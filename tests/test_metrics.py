"""Unit + end-to-end coverage for the unified metrics/trace plane (ISSUE 3).

Three layers:

1. Registry unit tests — thread-safety of every metric kind, histogram
   bucket semantics, the label-cardinality bound, mounts, and the emitter.
2. The snapshot guard — ``snapshot()`` must stay JSON-serializable and
   stable-keyed, because ``bench.py`` embeds it in ``BENCH_*.json`` and
   run-over-run diffs rely on a fixed key set.
3. Scheduler integration — a deterministic scripted request/fault sequence
   against the FakeServer harness asserting exact snapshot counters and
   trace contents, plus a seeded wedge storm over real UDP asserting the
   queue-age alarm's trace dump names the wedged miner and the speculative
   re-issue that resolved the stall, and that every replied request's
   trace is closed (span completeness).
"""

import asyncio
import json
import logging
import threading

from distributed_bitcoinminer_tpu.bitcoin.message import MsgType
from distributed_bitcoinminer_tpu.utils.config import LeaseParams
from distributed_bitcoinminer_tpu.utils.metrics import (
    Emitter, Registry, RequestTrace, TraceBuffer, ensure_emitter,
    registry as process_registry)

from tests.test_scheduler_recovery import (CLIENT_X, CLIENT_Y, MINER_A,
                                           MINER_B, MINER_C, FakeServer,
                                           join, make_scheduler, request,
                                           result)


# ------------------------------------------------------------ registry units


def test_counter_and_gauge_basics():
    r = Registry()
    c = r.counter("events")
    c.inc()
    c.inc(4)
    assert c.value == 5
    assert r.counter("events") is c          # same series, same object
    g = r.gauge("depth")
    g.set(3)
    g.inc(2)
    assert g.value == 5.0
    labeled = r.counter("events", kind="x")
    labeled.inc()
    assert labeled is not c and labeled.value == 1


def test_thread_safety_exact_totals():
    """8 writers x 5000 increments must lose nothing — the miner updates
    from worker threads while the asyncio loop updates from the event
    loop, so '+=' without the registry lock would drop counts."""
    r = Registry()
    c = r.counter("hot")
    h = r.histogram("lat", buckets=(0.5, 1.0))
    e = r.ewma("rate")

    def hammer():
        for _ in range(5000):
            c.inc()
            h.observe(0.25)
            e.observe(1.0)

    threads = [threading.Thread(target=hammer) for _ in range(8)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert c.value == 40_000
    assert h.count == 40_000
    assert h._snap()["counts"][0] == 40_000
    assert e.value == 1.0


def test_histogram_bucket_semantics():
    r = Registry()
    h = r.histogram("h", buckets=(1.0, 2.0, 4.0))
    for v in (0.5, 1.0, 3.0, 100.0):
        h.observe(v)
    snap = h._snap()
    assert snap["le"] == [1.0, 2.0, 4.0]
    # Cumulative: <=1 holds 0.5 and the boundary value 1.0; <=2 the same;
    # <=4 adds 3.0; 100.0 only shows in the +Inf total.
    assert snap["counts"] == [2, 2, 3]
    assert snap["count"] == 4
    assert abs(snap["sum"] - 104.5) < 1e-9


def test_label_cardinality_bound_collapses_to_overflow():
    r = Registry(max_series=4)
    for i in range(10):
        r.counter("conns", conn=str(i)).inc()
    snap = r.snapshot()
    series = [k for k in snap["counters"] if k.startswith("conns")]
    assert len(series) == 5                      # 4 real + 1 overflow
    assert "conns{overflow=true}" in series
    # The 6 collapsed label sets all landed on the overflow series;
    # series_overflow counts LOOKUPS routed there (one each here).
    assert snap["counters"]["conns{overflow=true}"] == 6
    assert snap["series_overflow"] == 6
    r.counter("conns", conn="99").inc()          # another overflow lookup
    assert r.snapshot()["series_overflow"] == 7


def test_remove_frees_series_and_cardinality_slot():
    """Dropping a labeled series (a dead miner's gauges) must take it out
    of snapshots AND free its slot under the cardinality bound, so churn
    of short-lived label values cannot exhaust a family."""
    r = Registry(max_series=2)
    r.gauge("rate", miner="1").set(10)
    r.gauge("rate", miner="2").set(20)
    r.remove("rate", miner="1")
    assert "rate{miner=1}" not in r.snapshot()["gauges"]
    r.gauge("rate", miner="3").set(30)       # reuses the freed slot
    snap = r.snapshot()
    assert snap["gauges"]["rate{miner=3}"] == 30
    assert snap["series_overflow"] == 0
    r.remove("rate", miner="nonexistent")    # no-op, no error


def test_miner_drop_retires_labeled_gauges():
    sched, _server = make_scheduler()
    join(sched, MINER_A)
    join(sched, MINER_B)
    request(sched, CLIENT_X, "churn", 199)
    # Backdate the assignments past RATE_WINDOW_S: the windowed sampler
    # (ISSUE 5) only publishes the rate gauges once a window's worth of
    # wall clock has been observed, and the scripted result is instant.
    for m in (sched._find_miner(MINER_A), sched._find_miner(MINER_B)):
        for ch in m.pending:
            ch.assigned_at -= 2 * sched.RATE_WINDOW_S
    result(sched, MINER_A)
    result(sched, MINER_B)
    assert "miner_rate_nps{miner=1}" in sched.metrics.snapshot()["gauges"]
    sched._on_drop(MINER_A)
    gauges = sched.metrics.snapshot()["gauges"]
    assert "miner_rate_nps{miner=1}" not in gauges
    assert "miner_rate_nps{miner=2}" in gauges


def test_ewma_moves_toward_samples():
    r = Registry()
    e = r.ewma("rate", tau_s=0.001)     # tiny tau: near-full weight per obs
    e.observe(10.0)
    assert e.value == 10.0
    e.observe(10.0)
    assert e.value == 10.0
    e.observe(0.0)
    assert 0.0 <= e.value < 10.0


def test_snapshot_json_serializable_and_stable_keyed():
    """The BENCH-diff guard (ISSUE 3 satellite): snapshots must round-trip
    through JSON unchanged and keep an identical, sorted key set as values
    evolve."""
    r = Registry()
    r.counter("a.count").inc()
    r.counter("a.count", k="v").inc(2)
    r.gauge("b.gauge").set(1.5)
    r.histogram("c.hist").observe(0.2)
    r.ewma("d.rate").observe(3.0)
    snap1 = r.snapshot()
    assert json.loads(json.dumps(snap1)) == snap1     # JSON-native only
    for section in ("counters", "gauges", "histograms", "ewmas"):
        keys = list(snap1[section])
        assert keys == sorted(keys)
    r.counter("a.count").inc(10)
    r.histogram("c.hist").observe(5.0)
    snap2 = r.snapshot()
    for section in ("counters", "gauges", "histograms", "ewmas"):
        assert list(snap1[section]) == list(snap2[section])  # stable keys
    assert snap2["counters"]["a.count"] == 11
    # The process registry (with the scheduler mounted by other tests)
    # satisfies the same guard.
    assert json.loads(json.dumps(process_registry().snapshot())) \
        == process_registry().snapshot()


def test_mount_prefixes_and_replaces():
    parent, child1, child2 = Registry(), Registry(), Registry()
    child1.counter("jobs").inc(3)
    parent.mount("sub", child1)
    snap = parent.snapshot()
    assert snap["counters"]["sub.jobs"] == 3
    child2.counter("jobs").inc(7)
    parent.mount("sub", child2)                  # latest mount wins
    assert parent.snapshot()["counters"]["sub.jobs"] == 7


def test_emitter_logs_json_lines(caplog):
    r = Registry()
    r.counter("ticks").inc()
    logger = logging.getLogger("test.dbm.metrics.emitter")
    em = Emitter(r, interval_s=0.02, logger=logger)
    with caplog.at_level(logging.INFO, logger=logger.name):
        em.start()
        em._stop.wait(0.2)
        em.stop()           # emits the final line
    docs = []
    for rec in caplog.records:
        try:
            docs.append(json.loads(rec.getMessage()))
        except ValueError:
            pass
    assert docs, "no JSON metric lines emitted"
    assert all(d["event"] == "metrics" for d in docs)
    assert docs[-1]["final"] is True
    assert docs[-1]["snapshot"]["counters"]["ticks"] == 1


def test_ensure_emitter_is_idempotent_and_zero_disables():
    assert ensure_emitter(0) is None
    assert ensure_emitter(-1) is None
    em1 = ensure_emitter(600.0)
    em2 = ensure_emitter(600.0)
    assert em1 is not None and em1 is em2


# --------------------------------------------------------------- trace units


def test_trace_events_closure_and_dict():
    t = RequestTrace(data="x", client=7)
    t.event("enqueue", queue_depth=0)
    assert not t.closed
    t.event("reply", nonce=5)
    assert t.closed
    d = t.to_dict()
    assert json.loads(json.dumps(d)) == d
    assert [e["event"] for e in d["events"]] == ["enqueue", "reply"]
    assert d["meta"]["client"] == 7
    assert d["events"][0]["t"] <= d["events"][1]["t"]


def test_trace_buffer_lru_bound():
    buf = TraceBuffer(cap=2)
    for i in range(4):
        tr = buf.new(i=i)
        tr.event("reply")
        buf.register(i, tr)
    assert len(buf) == 2
    assert buf.get(0) is None and buf.get(1) is None
    assert buf.get(3) is not None


def test_trace_buffer_pins_open_traces_against_bursts():
    """A burst of short-lived closed traces (the cache-replay retry-storm
    shape) must evict closed entries, never the live in-flight request's
    still-open trace — the record the alarm dump exists to preserve."""
    buf = TraceBuffer(cap=3)
    live = buf.new(job=1)
    live.event("dispatch")            # open: no terminal event yet
    buf.register(1, live)
    for i in range(10):               # 10 cache replays churn through
        tr = buf.new(i=i)
        tr.event("reply")
        buf.register(f"cache:{i}", tr)
    assert buf.get(1) is live         # survived the burst
    assert len(buf) == 3


def test_trace_event_cap_counts_drops_but_still_closes():
    t = RequestTrace()
    for _ in range(RequestTrace.MAX_EVENTS + 10):
        t.event("tick")
    assert len(t.events) == RequestTrace.MAX_EVENTS
    assert t.to_dict()["events_dropped"] == 10
    # Terminal events bypass the cap: an event-flooded trace must still
    # close when the request finally replies.
    t.event("reply", nonce=1)
    assert t.closed
    assert t.events[-1]["event"] == "reply"


# -------------------------------------------- scheduler snapshot (scripted)


def test_scheduler_snapshot_after_scripted_fault_sequence():
    """Deterministic end-to-end: a scripted request/fault sequence (lease
    blow -> re-issue -> duplicate -> cache replay) must land EXACTLY these
    numbers in the scheduler's registry snapshot, and the same values must
    be visible through the process registry mount."""
    sched, server = make_scheduler(grace_s=30.0, quarantine_after=1)
    join(sched, MINER_A)
    join(sched, MINER_B)
    join(sched, MINER_C)
    request(sched, CLIENT_X, "scripted", 299)          # 3 chunks
    a = sched._find_miner(MINER_A)
    stuck = a.pending[0]
    result(sched, MINER_C, h=50, nonce=7)              # C frees
    stuck.deadline = 0.0                               # force A's expiry
    sched._check_leases()                              # blow + reissue to C
    result(sched, MINER_C, h=40, nonce=2)              # the copy answers
    result(sched, MINER_A, h=40, nonce=2)              # loser: duplicate
    result(sched, MINER_B, h=60, nonce=9)              # barrier completes
    request(sched, CLIENT_Y, "scripted", 299)          # identical: memo hit
    snap = sched.metrics.snapshot()
    c = snap["counters"]
    assert c["results_sent"] == 2
    assert c["leases_blown"] == 1
    assert c["leases_blown_spurious"] == 0
    assert c["reissues"] == 1
    assert c["dup_results"] == 1
    assert c["quarantines"] == 1
    assert c["cache_hits"] == 1
    assert c["cache_stores"] == 1
    # Exactly ONE miss: the fresh request at enqueue. The dispatch-time
    # re-check of the same key is not double-counted, so the hit ratio
    # reflects distinct lookups (1 hit / 2 lookups = 0.5 here).
    assert c["cache_misses"] == 1
    assert c["desperation_dispatch"] == 0
    assert snap["gauges"]["queue_depth"] == 0
    assert snap["gauges"]["pool_size"] == 3
    assert snap["histograms"]["queue_wait_s"]["count"] == 1
    assert 0.0 < snap["gauges"]["cache_hit_ratio"] < 1.0
    assert json.loads(json.dumps(snap)) == snap
    # Mounted view: the process snapshot carries the same series under
    # the "sched." prefix (this scheduler is the latest mount).
    proc = process_registry().snapshot()
    assert proc["counters"]["sched.results_sent"] == 2
    assert proc["counters"]["sched.reissues"] == 1

    # Trace plane: job 1's span is complete and explains the fault.
    t = sched.trace(1)
    assert t is not None and t.closed
    events = t.to_dict()["events"]
    names = [e["event"] for e in events]
    assert names[0] == "enqueue" and names[-1] == "reply"
    assert "dispatch" in names and "merge" in names
    blow = next(e for e in events if e["event"] == "lease_blown")
    assert blow["miner"] == MINER_A and blow["spurious"] is False
    reissue = next(e for e in events if e["event"] == "reissue")
    assert reissue["from_miner"] == MINER_A
    assert reissue["to_miner"] == MINER_C
    dup = [e for e in events if e["event"] == "result"
           and e.get("duplicate")]
    assert len(dup) == 1 and dup[0]["miner"] == MINER_A
    # The memo replay is traced too, under its synthetic key.
    ct = sched.trace("cache:1")
    assert ct is not None and ct.closed
    assert [e["event"] for e in ct.to_dict()["events"]] == \
        ["enqueue", "cache_hit", "reply"]


def test_dispatch_time_cache_replay_keeps_real_trace_history():
    """A retry that queued behind its in-flight original and replays from
    the memo at dispatch must complete its OWN trace (real enqueue stamp,
    real queue wait observed) — not a synthetic zero-age stand-in."""
    sched, server = make_scheduler()
    join(sched, MINER_A)
    request(sched, CLIENT_X, "dup race", 99)   # in flight
    request(sched, CLIENT_Y, "dup race", 99)   # queued duplicate
    waited_before = sched.metrics.histogram("queue_wait_s").count
    result(sched, MINER_A, h=5, nonce=2)       # finish + store + pop queue
    assert len(server.sent_to(CLIENT_Y, MsgType.RESULT)) == 1
    ct = sched.trace("cache:1")
    assert ct is not None and ct.closed
    events = [e["event"] for e in ct.to_dict()["events"]]
    assert events == ["enqueue", "cache_hit", "reply"]
    assert ct.to_dict()["meta"]["client"] == CLIENT_Y  # the real request
    # The queue wait it actually served was observed (the original's was
    # already recorded at its own dispatch, before the snapshot above).
    assert sched.metrics.histogram("queue_wait_s").count == \
        waited_before + 1


def test_queue_age_alarm_dumps_traces(caplog):
    """A stalled queued request's alarm must dump its own trace AND the
    in-flight request's trace (the usual culprit), as parseable JSON."""
    sched, _server = make_scheduler(queue_alarm_s=5.0)
    join(sched, MINER_A)
    request(sched, CLIENT_X, "in flight", 99)
    request(sched, CLIENT_Y, "stuck behind", 199)
    sched.queue[0].queued_at -= 100.0
    sched.current.started -= 100.0
    with caplog.at_level(logging.WARNING, logger="dbm.scheduler"):
        sched._check_queue_age()
    assert sched.stats["queue_alarms"] == 1
    assert sched.stats["inflight_alarms"] == 1
    dumps = [r.getMessage() for r in caplog.records
             if "trace dump" in r.getMessage()]
    # Stalled request + in-flight request, each dumped exactly ONCE: the
    # "in flight ahead of the stalled one" dump is suppressed when the
    # in-flight alarm dumps the identical document in the same sweep.
    assert len(dumps) == 2
    parsed = [json.loads(d[d.index("{"):]) for d in dumps]
    # The in-flight trace names the miner holding the pool.
    flight = next(p for p in parsed
                  if any(e["event"] == "assign" for e in p["events"]))
    assign = next(e for e in flight["events"] if e["event"] == "assign")
    assert assign["miner"] == MINER_A


# ------------------------------------------------- chaos e2e (real UDP pool)


def test_chaos_wedge_alarm_trace_names_culprit_and_rescue():
    """ISSUE 3 acceptance: a scripted wedge storm produces a queue-age
    alarm whose dumped trace names the wedged miner and the speculative
    re-issue that resolved it; every replied request's span is closed."""
    from tests.test_chaos import ChaosCluster, expected
    from distributed_bitcoinminer_tpu.apps.client import submit

    lease = LeaseParams(grace_s=0.6, factor=4.0, floor_s=0.3, tick_s=0.05,
                        quarantine_after=3, ewma_alpha=0.5,
                        queue_alarm_s=0.2)

    async def scenario():
        async with ChaosCluster(lease=lease) as c:
            wedged = await c.add_miner("wedged")
            await c.add_miner("healthy")
            wedged_conn = wedged.conn_id
            wedged.wedge()                    # compute hangs; LSP lives
            t1 = asyncio.create_task(
                submit(c.hostport, "stall one", 799, c.params))
            await asyncio.sleep(0.1)          # t1 is in flight first
            t2 = asyncio.create_task(
                submit(c.hostport, "stall two", 399, c.params))
            r1 = await asyncio.wait_for(t1, 30)
            r2 = await asyncio.wait_for(t2, 30)
            assert r1 == expected("stall one", 799)
            assert r2 == expected("stall two", 399)
            s = c.scheduler
            # The stall was loud: t2 sat behind the wedged request past
            # the 0.2s bound while the lease (0.6s grace) ran down.
            assert s.stats["queue_alarms"] + s.stats["inflight_alarms"] \
                >= 1
            # The dumped/retrievable trace explains the stall: the wedged
            # miner blew the lease and the re-issue rescued the chunk.
            events = s.trace(1).to_dict()["events"]
            blows = [e for e in events if e["event"] == "lease_blown"]
            assert any(e["miner"] == wedged_conn for e in blows)
            reissues = [e for e in events if e["event"] == "reissue"]
            assert any(e["from_miner"] == wedged_conn for e in reissues)
            assert events[-1]["event"] == "reply"
            # Span completeness: every replied request's trace is closed
            # (the wedged job's late duplicate never reopens it).
            for _key, tr in s.traces.items():
                assert tr.closed, f"unclosed trace {_key}"
            wedged.unwedge()
            assert await c.settle()
    asyncio.run(scenario())


# ------------------------------------------------- configure_logging bugfix


def test_configure_logging_idempotent_and_symmetric(tmp_path):
    """ISSUE 3 satellite: re-configuration must not clear/re-add handlers
    (duplicate or dropped lines), must leave foreign handlers alone, and
    packet_trace=False must disable a previously-enabled trace."""
    from distributed_bitcoinminer_tpu.utils import logging as dbm_logging
    from distributed_bitcoinminer_tpu.lspnet.faults import knobs

    logger = logging.getLogger("dbm")
    before = list(logger.handlers)
    try:
        lg = dbm_logging.configure_logging(packet_trace=True)
        assert lg is logger and knobs.debug
        ours = dbm_logging._installed["handler"]
        n = len(logger.handlers)
        lg2 = dbm_logging.configure_logging(packet_trace=False)
        assert lg2 is logger
        assert len(logger.handlers) == n                  # no duplicates
        assert dbm_logging._installed["handler"] is ours  # same handler
        assert not knobs.debug                            # symmetric off
        foreign = logging.NullHandler()
        logger.addHandler(foreign)
        dbm_logging.configure_logging(logfile=str(tmp_path / "dbm.log"))
        assert foreign in logger.handlers        # foreign sink untouched
        assert dbm_logging._installed["handler"] is not ours  # ours swapped
        assert len(logger.handlers) == n + 1
    finally:
        ours = dbm_logging._installed["handler"]
        if ours is not None:
            logger.removeHandler(ours)
            ours.close()
        dbm_logging._installed["handler"] = None
        dbm_logging._installed["sink"] = None
        logger.handlers = before
