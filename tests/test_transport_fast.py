"""Transport fast-path conformance (ISSUE 17).

Three planes, each pinned against the STOCK codepath it replaces:

- the wire codec (``lsp/wire.py``): fuzzed round-trip equality against
  ``Message.to_json``/``from_json`` — byte-for-byte frames, identical
  accept/reject language on corrupt and truncated input;
- the batched-syscall endpoint (``lsp/_mmsg.py`` + ``lspnet/net.py``
  ``MmsgEndpoint``): burst send/recv through real sockets, knob gating,
  burst drain via ``recv_nowait``, and the syscall/datagram counter
  economics;
- the hoisted metric handles (``lspnet/faults.py``/``net.py``) and the
  ``hotpath-alloc`` dbmlint analyzer that keeps the marked functions
  allocation-lean.

The tier-1 knob-off matrix leg re-runs this module with ``DBM_MMSG=0
DBM_WIRE_FAST=0``: every parity assertion then exercises stock-vs-stock
(trivially equal) while the LIVE traffic tests cover the stock
transport — both datapaths stay green both ways.
"""

import asyncio
import base64
import json
import random

import pytest

from distributed_bitcoinminer_tpu import lspnet
from distributed_bitcoinminer_tpu.analysis.core import run_source
from distributed_bitcoinminer_tpu.lsp import _mmsg, wire
from distributed_bitcoinminer_tpu.lsp.checksum import make_checksum
from distributed_bitcoinminer_tpu.lsp.message import (Message, MsgType,
                                                      new_ack, new_connect,
                                                      new_data)
from distributed_bitcoinminer_tpu.lspnet import faults
from distributed_bitcoinminer_tpu.lspnet.net import (MmsgEndpoint,
                                                     UDPEndpoint)
from distributed_bitcoinminer_tpu.utils.metrics import registry

_LSP_REL = "distributed_bitcoinminer_tpu/lsp/_fixture.py"


def _random_message(rng):
    kind = rng.randrange(3)
    if kind == 0:
        return new_connect()
    conn_id = rng.choice([0, 1, 7, 65535, 2 ** 31 - 1])
    seq = rng.choice([0, 1, 255, 10 ** 6])
    if kind == 2:
        return new_ack(conn_id, seq)
    payload = bytes(rng.randrange(256)
                    for _ in range(rng.choice([0, 1, 2, 3, 16, 127, 1400])))
    return new_data(conn_id, seq, len(payload), payload,
                    make_checksum(conn_id, seq, len(payload), payload))


class TestWireFuzzConformance:
    """Satellite 1: random valid Messages through the fast serializer and
    parser must be indistinguishable from the stock codec."""

    def test_encode_matches_to_json_bytes(self):
        rng = random.Random(0x17)
        for _ in range(500):
            msg = _random_message(rng)
            assert wire.encode(msg) == msg.to_json()

    def test_hot_encoders_match_stock_constructors(self):
        rng = random.Random(0x18)
        for _ in range(200):
            payload = bytes(rng.randrange(256)
                            for _ in range(rng.randrange(64)))
            cid, seq = rng.randrange(10 ** 6), rng.randrange(10 ** 6)
            ck = make_checksum(cid, seq, len(payload), payload)
            assert wire.encode_data(cid, seq, len(payload), ck, payload) \
                == new_data(cid, seq, len(payload), payload, ck).to_json()
            assert wire.encode_ack(cid, seq) == new_ack(cid, seq).to_json()
        assert wire.encode_connect() == new_connect().to_json()

    def test_decode_round_trip_equality(self):
        rng = random.Random(0x19)
        for _ in range(500):
            msg = _random_message(rng)
            raw = msg.to_json()
            got = wire.decode(raw)
            ref = Message.from_json(raw)
            assert (got.type, got.conn_id, got.seq_num, got.size,
                    got.checksum, got.payload) == \
                   (ref.type, ref.conn_id, ref.seq_num, ref.size,
                    ref.checksum, ref.payload)

    def test_checksum_matches_stock(self):
        rng = random.Random(0x1A)
        cases = [b"", b"\x00", b"\x00\x00", b"\xff" * 64, b"ab"]
        cases += [bytes(rng.randrange(256) for _ in range(rng.randrange(200)))
                  for _ in range(300)]
        for payload in cases:
            cid, seq = rng.randrange(2 ** 16), rng.randrange(2 ** 16)
            assert wire.checksum(cid, seq, len(payload), payload) == \
                make_checksum(cid, seq, len(payload), payload)

    def test_truncated_frames_drop_exactly_like_stock(self):
        rng = random.Random(0x1B)
        for _ in range(60):
            raw = _random_message(rng).to_json()
            for cut in range(0, len(raw)):
                broken = raw[:cut]
                try:
                    ref = Message.from_json(broken)
                except ValueError:
                    with pytest.raises(ValueError):
                        wire.decode(broken)
                else:  # pragma: no cover — no truncation parses today
                    got = wire.decode(broken)
                    assert got.type == ref.type

    def test_corrupt_frames_drop_exactly_like_stock(self):
        rng = random.Random(0x1C)
        for _ in range(200):
            raw = bytearray(_random_message(rng).to_json())
            pos = rng.randrange(len(raw))
            raw[pos] = rng.randrange(256)
            broken = bytes(raw)
            try:
                ref = Message.from_json(broken)
            except ValueError:
                with pytest.raises(ValueError):
                    wire.decode(broken)
            else:
                got = wire.decode(broken)
                assert (got.type, got.conn_id, got.seq_num, got.size,
                        got.checksum, got.payload) == \
                       (ref.type, ref.conn_id, ref.seq_num, ref.size,
                        ref.checksum, ref.payload)

    def test_invalid_base64_alphabet_rejected_like_stock(self):
        msg = new_data(1, 2, 4, b"abcd", make_checksum(1, 2, 4, b"abcd"))
        raw = msg.to_json()
        bad = raw.replace(base64.b64encode(b"abcd"), b"a*cd=!")
        with pytest.raises(ValueError):
            Message.from_json(bad)
        with pytest.raises(ValueError):
            wire.decode(bad)

    def test_non_canonical_layout_falls_back(self):
        # Reordered keys and whitespace are valid stock JSON; the scanner
        # must fall back, not reject.
        obj = {"ConnID": 3, "Type": 2, "SeqNum": 9, "Size": 0,
               "Checksum": 0, "Payload": None}
        raw = json.dumps(obj).encode()
        got = wire.decode(raw)
        assert (got.type, got.conn_id, got.seq_num) == (MsgType.ACK, 3, 9)

    def test_knob_off_routes_to_stock(self, monkeypatch):
        monkeypatch.setenv("DBM_WIRE_FAST", "0")
        wire.refresh()
        try:
            assert not wire.fast_enabled()
            msg = new_data(1, 1, 2, b"ok", make_checksum(1, 1, 2, b"ok"))
            assert wire.encode(msg) == msg.to_json()
            assert wire.checksum(1, 1, 2, b"ok") == \
                make_checksum(1, 1, 2, b"ok")
        finally:
            monkeypatch.delenv("DBM_WIRE_FAST")
            wire.refresh()


@pytest.mark.skipif(not _mmsg.available(),
                    reason="recvmmsg/sendmmsg unavailable")
class TestMmsgSocket:
    """The raw syscall wrapper: one syscall per burst, both directions."""

    def _socket_pair(self):
        import socket
        a = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
        b = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
        a.bind(("127.0.0.1", 0))
        b.bind(("127.0.0.1", 0))
        a.setblocking(False)
        b.setblocking(False)
        return a, b

    def test_burst_round_trip_with_addrs(self):
        a, b = self._socket_pair()
        try:
            ma = _mmsg.MmsgSocket(a.fileno(), 8)
            mb = _mmsg.MmsgSocket(b.fileno(), 8)
            addr_b = b.getsockname()
            frames = [b"frame-%d" % i for i in range(5)]
            sent = ma.send_burst([(f, addr_b) for f in frames])
            assert sent == 5
            import time
            deadline = time.monotonic() + 2
            got = []
            while len(got) < 5 and time.monotonic() < deadline:
                got.extend(mb.recv_burst())
            assert sorted(data for data, _ in got) == sorted(frames)
            # Every datagram came from a's bound address, via the cache.
            addrs = {addr for _, addr in got}
            assert addrs == {a.getsockname()}
        finally:
            a.close()
            b.close()

    def test_connected_socket_addr_none(self):
        a, b = self._socket_pair()
        try:
            a.connect(b.getsockname())
            ma = _mmsg.MmsgSocket(a.fileno(), 4)
            mb = _mmsg.MmsgSocket(b.fileno(), 4)
            assert ma.send_burst([(b"hello", None)]) == 1
            import time
            deadline = time.monotonic() + 2
            got = []
            while not got and time.monotonic() < deadline:
                got = mb.recv_burst()
            assert got[0][0] == b"hello"
        finally:
            a.close()
            b.close()

    def test_empty_socket_returns_empty(self):
        a, _b = self._socket_pair()
        try:
            ma = _mmsg.MmsgSocket(a.fileno(), 4)
            assert ma.recv_burst() == []
        finally:
            a.close()
            _b.close()

    def test_send_burst_caps_at_batch(self):
        a, b = self._socket_pair()
        try:
            ma = _mmsg.MmsgSocket(a.fileno(), 3)
            addr = b.getsockname()
            sent = ma.send_burst([(b"x", addr)] * 7)
            assert sent == 3
        finally:
            a.close()
            b.close()


class TestEndpointSelection:
    """Knob gating and graceful fallback of the batched endpoint."""

    def test_default_endpoint_kind_matches_knob(self, monkeypatch):
        async def scenario(expect_mmsg):
            server = await lspnet.listen_udp()
            client = await lspnet.dial_udp("127.0.0.1", server.sockname[1])
            try:
                for ep in (server, client):
                    assert isinstance(ep, MmsgEndpoint) == expect_mmsg
                    assert isinstance(ep, UDPEndpoint)
            finally:
                server.close()
                client.close()

        import os
        knob_on = os.environ.get("DBM_MMSG", "1") != "0"
        if _mmsg.available() and knob_on:
            asyncio.run(scenario(True))
        monkeypatch.setenv("DBM_MMSG", "0")
        asyncio.run(scenario(False))

    def test_live_traffic_and_counters(self):
        """Counter-equality pin (ISSUE 17 satellite): the per-direction
        syscall/datagram/byte counters move together, and the stock path
        is truthfully 1:1 while the mmsg path never exceeds it."""
        def snap():
            c = registry().snapshot()["counters"]
            return {k: c.get(k, 0) for k in (
                "net.syscalls{dir=send}", "net.datagrams{dir=send}",
                "net.bytes{dir=send}", "net.datagrams{dir=recv}",
                "net.bytes{dir=recv}")}

        async def scenario():
            server = await lspnet.listen_udp()
            client = await lspnet.dial_udp("127.0.0.1", server.sockname[1])
            before = snap()
            n, frame = 10, b"y" * 33
            for _ in range(n):
                client.send(frame)
            got = 0
            while got < n:
                item = await asyncio.wait_for(server.recv(), 2)
                assert item is not None
                got += 1
                item = server.recv_nowait()
                while item is not None:
                    got += 1
                    item = server.recv_nowait()
            await asyncio.sleep(0.05)   # let any queued flush run
            after = snap()
            server.close()
            client.close()
            return before, after

        before, after = asyncio.run(scenario())
        d = {k: after[k] - before[k] for k in before}
        assert d["net.datagrams{dir=send}"] >= 10
        assert d["net.datagrams{dir=recv}"] >= 10
        assert d["net.bytes{dir=send}"] >= 10 * 33
        assert d["net.bytes{dir=recv}"] >= 10 * 33
        # Syscalls never exceed datagrams (stock is exactly 1:1; the
        # batched path amortizes below it).
        assert 0 < d["net.syscalls{dir=send}"] <= d["net.datagrams{dir=send}"]

    def test_recv_nowait_preserves_close_sentinel(self):
        async def scenario():
            server = await lspnet.listen_udp()
            server.close()
            # recv_nowait must not eat the sentinel...
            assert server.recv_nowait() is None
            # ...so the awaited recv still observes the close.
            assert await asyncio.wait_for(server.recv(), 2) is None
            return True

        assert asyncio.run(scenario())


class TestHoistedFaultHandles:
    """Satellite 4: partition episodes count through the module-scope
    handle — identical counter, no per-call registry lookup."""

    def test_partition_episode_counter_equality(self):
        handle = registry().counter("net.partitions_opened")
        assert faults._MET_PARTITIONS_OPENED is handle
        faults.heal_all_partitions()
        base = handle.value
        try:
            faults.partition_conn(90001)
            assert handle.value == base + 1
            # Re-applying an open partition is NOT a new episode.
            faults.partition_conn(90001)
            assert handle.value == base + 1
            faults.heal_conn(90001)
            faults.partition_conn(90001, inbound=True, outbound=False)
            assert handle.value == base + 2
        finally:
            faults.heal_all_partitions()


class TestHotpathAllocAnalyzer:
    """Satellite 2: the dbmlint analyzer that keeps marked functions
    allocation-lean."""

    def _findings(self, src):
        return run_source("hotpath-alloc", src, rel=_LSP_REL)

    def test_json_dumps_in_marked_function_flagged(self):
        src = ("import json\n"
               "# dbmlint: hotpath\n"
               "def enc(x):\n"
               "    return json.dumps(x)\n")
        found = self._findings(src)
        assert len(found) == 1 and "json.dumps" in found[0].message

    def test_dict_and_list_literals_flagged_once_per_kind(self):
        src = ("# dbmlint: hotpath\n"
               "def enc(x):\n"
               "    a = {'k': x}\n"
               "    b = {'j': x}\n"
               "    c = [x, x]\n"
               "    return a, b, c\n")
        codes = sorted(f.key.rsplit(":", 1)[1] for f in self._findings(src))
        assert codes == ["dict-literal", "list-literal"]

    def test_base64_call_flagged(self):
        src = ("import base64\n"
               "# dbmlint: hotpath\n"
               "def enc(x):\n"
               "    return base64.b64encode(x)\n")
        found = self._findings(src)
        assert len(found) == 1 and "binascii" in found[0].message

    def test_unmarked_function_silent(self):
        src = ("import json\n"
               "def enc(x):\n"
               "    return json.dumps({'k': x})\n")
        assert self._findings(src) == []

    def test_out_of_scope_file_silent(self):
        src = ("import json\n"
               "# dbmlint: hotpath\n"
               "def enc(x):\n"
               "    return json.dumps(x)\n")
        rel = "distributed_bitcoinminer_tpu/apps/_fixture.py"
        assert run_source("hotpath-alloc", src, rel=rel) == []

    def test_marker_on_def_line_and_above_decorator(self):
        src = ("def deco(f):\n"
               "    return f\n"
               "# dbmlint: hotpath\n"
               "@deco\n"
               "def enc(x):\n"
               "    return [x]\n"
               "def enc2(x):  # dbmlint: hotpath\n"
               "    return [x]\n")
        found = self._findings(src)
        assert sorted(f.key.split(":")[-2] for f in found) == ["enc", "enc2"]

    def test_suppression_comment_honored(self):
        src = ("# dbmlint: hotpath\n"
               "def enc(x):\n"
               "    return [x]  # dbmlint: ok[hotpath-alloc] cold branch\n")
        assert self._findings(src) == []

    def test_nested_def_inside_marked_function_not_exempt(self):
        src = ("# dbmlint: hotpath\n"
               "def enc(x):\n"
               "    def inner():\n"
               "        return {'k': x}\n"
               "    return inner\n")
        found = self._findings(src)
        assert len(found) == 1 and "dict" in found[0].message

    def test_real_wire_module_is_clean(self):
        import distributed_bitcoinminer_tpu.lsp.wire as wire_mod
        with open(wire_mod.__file__, encoding="utf-8") as fh:
            src = fh.read()
        rel = "distributed_bitcoinminer_tpu/lsp/wire.py"
        assert run_source("hotpath-alloc", src, rel=rel) == []
