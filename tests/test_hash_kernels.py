"""Compute-kernel equivalence: every tier must be bit-identical to hashlib.

The oracle is ``bitcoin.hash_op`` (= first 8 bytes of
sha256(f"{data} {nonce}") big-endian, ref: bitcoin/hash.go:13-17).
"""

import hashlib

import numpy as np
import pytest

from distributed_bitcoinminer_tpu.bitcoin.hash import hash_op, scan_min
from distributed_bitcoinminer_tpu.models import NonceSearcher
from distributed_bitcoinminer_tpu.ops.sha256_host import (
    SHA256_H0, compress_host, sha256_finish_host, sha256_midstate)


class TestHostSha256:
    @pytest.mark.parametrize("msg", [b"", b"abc", b"cmu440 0",
                                     b"x" * 55, b"y" * 56, b"z" * 64,
                                     b"w" * 119, b"v" * 120, b"u" * 500])
    def test_matches_hashlib(self, msg):
        midstate, tail = sha256_midstate(msg)
        digest = sha256_finish_host(midstate, tail, len(msg))
        assert digest == hashlib.sha256(msg).digest()

    def test_compress_is_incremental(self):
        msg = bytes(range(256))
        midstate, tail = sha256_midstate(msg)
        assert len(tail) == 0
        state = SHA256_H0
        for off in range(0, 256, 64):
            state = compress_host(state, msg[off:off + 64])
        assert state == midstate


class TestDeviceSearch:
    def test_small_range_matches_oracle(self):
        searcher = NonceSearcher("cmu440", batch=512)
        got_hash, got_nonce = searcher.search(0, 9999)
        want_hash, want_nonce = scan_min("cmu440", 0, 9999)
        assert (got_hash, got_nonce) == (want_hash, want_nonce)

    def test_range_not_from_zero(self):
        searcher = NonceSearcher("hello world", batch=256)
        got = searcher.search(123, 4567)
        want = scan_min("hello world", 123, 4567)
        assert got == want

    def test_range_spanning_digit_classes(self):
        # 8..1042 crosses the 1/2/3/4-digit boundaries.
        searcher = NonceSearcher("digit boundaries", batch=128)
        got = searcher.search(8, 1042)
        want = scan_min("digit boundaries", 8, 1042)
        assert got == want

    def test_large_nonces_use_top_digit_midstate(self):
        # d > 9: top digits absorbed in the midstate, k=9 low digits on device.
        base = 12_345_678_901  # 11 digits
        searcher = NonceSearcher("bigvals", batch=256)
        got = searcher.search(base, base + 2000)
        want = scan_min("bigvals", base, base + 2000)
        assert got == want

    def test_block_boundary_crossing(self):
        # Crosses an aligned 10^3... actually 10^9 is too big for a test;
        # cross the 10^k alignment inside one digit class: 999_990..1_000_010
        # crosses the 6->7 digit boundary AND the aligned block edge.
        searcher = NonceSearcher("edge", batch=64)
        got = searcher.search(999_990, 1_000_010)
        want = scan_min("edge", 999_990, 1_000_010)
        assert got == want

    def test_long_data_multiblock_prefix(self):
        # Prefix > 64 bytes: midstate absorbs full blocks; tail + digits may
        # straddle two device blocks.
        data = "m" * 100
        searcher = NonceSearcher(data, batch=128)
        got = searcher.search(0, 3000)
        want = scan_min(data, 0, 3000)
        assert got == want

    @pytest.mark.parametrize("tail_len", [53, 54, 55, 56, 63])
    def test_tail_pad_boundaries(self, tail_len):
        # rem + k near the one-vs-two-block padding boundary.
        data = "a" * (tail_len - 1)  # prefix = data + " " => rem = tail_len
        searcher = NonceSearcher(data, batch=64)
        got = searcher.search(0, 500)
        want = scan_min(data, 0, 500)
        assert got == want

    def test_single_nonce_range(self):
        searcher = NonceSearcher("one", batch=64)
        got = searcher.search(42, 42)
        assert got == (hash_op("one", 42), 42)

    def test_earliest_nonce_wins_ties(self):
        # Force a tie by duplicating: can't easily force SHA ties, but the
        # merge path is covered: equal hashes across batches keep the lower
        # nonce by strict-less merge. Verify via oracle over a range where
        # batch boundaries fall inside (batch smaller than range).
        searcher = NonceSearcher("tie-check", batch=32)
        got = searcher.search(0, 2047)
        want = scan_min("tie-check", 0, 2047)
        assert got == want

    def test_empty_data_string(self):
        searcher = NonceSearcher("", batch=64)
        got = searcher.search(0, 999)
        want = scan_min("", 0, 999)
        assert got == want

    def test_unicode_data(self):
        searcher = NonceSearcher("héllo wörld", batch=64)
        got = searcher.search(0, 999)
        want = scan_min("héllo wörld", 0, 999)
        assert got == want


class TestSubDispatchDecomposition:
    """The pow2 sub-dispatch decomposition (round 3): exact step counts —
    the round-1/2 single rounded-up dispatch overscanned up to 2x (the
    bench's 65-step range ran as 128 steps at half the measured rate)."""

    def test_exact_pow2_descending_contiguous(self):
        # [100, 999] is one 3-digit block; batch 64 -> i0 = 64 (aligned
        # below lo), span 936 -> 15 steps = 8+4+2+1 exactly.
        s = NonceSearcher("x", batch=64)
        plan = next(s.plan(100, 999))
        subs = s._sub_dispatches(plan)
        sizes = [n for _, n in subs]
        assert all(n & (n - 1) == 0 for n in sizes), sizes
        assert sizes == [8, 4, 2, 1]
        # contiguous: each sub starts where the previous ended
        assert subs[0][0] == 64
        for (i0a, na), (i0b, _) in zip(subs, subs[1:]):
            assert i0b == i0a + na * s.batch

    def test_odd_step_count_is_not_rounded_up(self):
        # span of 5 batches decomposes 4+1, not a rounded-up 8.
        s = NonceSearcher("x", batch=100)
        plan = next(s.plan(100, 599))
        assert [n for _, n in s._sub_dispatches(plan)] == [4, 1]

    def test_decomposed_search_exact_vs_oracle(self):
        # 5-batch + misaligned lo: exercises the multi-sub merge and the
        # below-lo masked head in the same search.
        s = NonceSearcher("decomp", batch=100)
        assert s.search(37, 480) == scan_min("decomp", 37, 480)

    def test_difficulty_mode_across_subs(self):
        # Target reachable only in the LAST sub of a 2+1 decomposition:
        # the host early-exit between subs must still return the globally
        # first qualifying nonce.
        from distributed_bitcoinminer_tpu.bitcoin.hash import hash_op
        s = NonceSearcher("untilsub", batch=128)
        lo, hi = 128, 511  # one 3-digit block, 3 batches -> subs [2, 1]
        assert [n for _, n in s._sub_dispatches(next(s.plan(lo, hi)))] == \
            [2, 1]
        hashes = {n: hash_op("untilsub", n) for n in range(lo, hi + 1)}
        # pick a target hit only inside the last sub's lanes [384, 511]
        target = min(h for n, h in hashes.items() if n >= 384) + 1
        first = next(n for n in range(lo, hi + 1) if hashes[n] < target)
        h, n, found = s.search_until(lo, hi, target)
        assert (found, n, h) == (True, first, hashes[first])


def test_dispatch_finalize_overlap_api():
    """The host<->device overlap API (SURVEY §7 double-buffering): several
    ranges enqueued before any result is forced must finalize to exactly
    the per-range sequential results, in any finalize order."""
    s = NonceSearcher("overlap", batch=256)
    ranges = [(0, 999), (1000, 2999), (100, 2047)]
    want = [s.search(lo, hi) for lo, hi in ranges]
    handles = [(s.dispatch(lo, hi), lo) for lo, hi in ranges]
    got = {i: s.finalize(h, lo) for i, (h, lo) in
           reversed(list(enumerate(handles)))}
    assert [got[i] for i in range(len(ranges))] == want
