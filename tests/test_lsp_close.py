"""Close/shutdown semantics + slow-start connect retries.

Port of the reference lsp3_test.go scenarios: a client connecting before the
server exists must keep retrying; Close flushes pending data both ways;
CloseConn is non-blocking; the other side observes clean termination errors;
loss detection fires after EpochLimit silent epochs.
"""

import asyncio

import pytest

from distributed_bitcoinminer_tpu import lspnet
from distributed_bitcoinminer_tpu.lsp import Params
from distributed_bitcoinminer_tpu.lsp.client import new_async_client
from distributed_bitcoinminer_tpu.lsp.errors import (
    ConnectionClosed, ConnectionLost, ConnectTimeout, LspError)
from distributed_bitcoinminer_tpu.lsp.server import new_async_server


def params_with(window=1, backoff=0, epoch_ms=50, limit=5):
    return Params(epoch_limit=limit, epoch_millis=epoch_ms,
                  window_size=window, max_backoff_interval=backoff)


class TestConnect:
    def test_connect_timeout_when_no_server(self):
        async def scenario():
            params = params_with(epoch_ms=40, limit=3)
            with pytest.raises(ConnectTimeout):
                # Port 1 on localhost: nothing listening.
                await new_async_client("127.0.0.1:1", params)
        asyncio.run(scenario())

    def test_server_slow_start(self):
        """Client keeps retrying Connect until a late server appears
        (ref TestServerSlowStart, lsp3_test.go:176-182)."""
        async def scenario():
            params = params_with(epoch_ms=50, limit=12)
            # Reserve a port, then release it for the late server.
            probe = await new_async_server(0, params)
            port = probe.port
            await probe.close()

            async def late_server():
                await asyncio.sleep(0.3)  # ~6 epochs late
                return await new_async_server(port, params)

            server_task = asyncio.create_task(late_server())
            client = await new_async_client(f"127.0.0.1:{port}", params)
            server = await server_task
            client.write(b"made it")
            _, payload = await asyncio.wait_for(server.read(), 5)
            assert payload == b"made it"
            await client.close()
            await server.close()
        asyncio.run(scenario())


class TestImplicitEstablish:
    def test_data_during_connecting_establishes_and_delivers(self):
        """Connect-ack lost, but server Data arrives first: the client must
        establish implicitly and deliver the message exactly once (regression:
        data consumed-but-undelivered while CONNECTING)."""
        async def scenario():
            import distributed_bitcoinminer_tpu.lspnet as lspnet
            params = params_with(epoch_ms=500, limit=10)
            server = await new_async_server(0, params)
            lspnet.set_server_write_drop_percent(100)  # connect ack vanishes

            connect_task = asyncio.create_task(
                new_async_client(f"127.0.0.1:{server.port}", params))
            # Wait until the server has seen the Connect (conn exists).
            for _ in range(100):
                if server._conns:
                    break
                await asyncio.sleep(0.01)
            assert server._conns, "server never saw the Connect"
            conn_id = next(iter(server._conns))
            lspnet.set_server_write_drop_percent(0)
            server.write(conn_id, b"early bird")

            client = await asyncio.wait_for(connect_task, 5)
            assert client.conn_id() == conn_id
            got = await asyncio.wait_for(client.read(), 5)
            assert got == b"early bird"
            # Exactly once: nothing further pending.
            client.write(b"reply")
            _, payload = await asyncio.wait_for(server.read(), 5)
            assert payload == b"reply"
            await client.close()
            await server.close()
        asyncio.run(scenario())


class TestClientClose:
    def test_close_flushes_pending_writes(self):
        """Writes issued immediately before Close must still arrive
        (ref TestClientClose / fast-close family)."""
        async def scenario():
            params = params_with(window=2, backoff=1, epoch_ms=50, limit=30)
            server = await new_async_server(0, params)
            client = await new_async_client(f"127.0.0.1:{server.port}", params)
            n = 10
            for i in range(n):
                client.write(f"m{i}".encode())
            await client.close()  # must block until all 10 acked
            got = []
            while len(got) < n:
                _, payload = await asyncio.wait_for(server.read(), 5)
                if isinstance(payload, bytes):
                    got.append(payload)
            assert got == [f"m{i}".encode() for i in range(n)]
            await server.close()
        asyncio.run(scenario())

    def test_read_after_close_raises(self):
        async def scenario():
            params = params_with()
            server = await new_async_server(0, params)
            client = await new_async_client(f"127.0.0.1:{server.port}", params)
            await client.close()
            with pytest.raises(LspError):
                await asyncio.wait_for(client.read(), 2)
            with pytest.raises(LspError):
                client.write(b"nope")
            await server.close()
        asyncio.run(scenario())

    def test_server_detects_closed_clients(self):
        """After clients vanish, server reads per-conn errors within
        EpochLimit epochs (ref TestClientClose2 / server-detect pattern)."""
        async def scenario():
            params = params_with(epoch_ms=40, limit=4)
            server = await new_async_server(0, params)
            clients = [await new_async_client(f"127.0.0.1:{server.port}", params)
                       for _ in range(3)]
            for i, c in enumerate(clients):
                c.write(f"hello{i}".encode())
            seen = 0
            while seen < 3:
                _, item = await asyncio.wait_for(server.read(), 5)
                if isinstance(item, bytes):
                    seen += 1
            for c in clients:
                await c.close()
            dead = set()
            while len(dead) < 3:
                conn_id, item = await asyncio.wait_for(server.read(), 5)
                if isinstance(item, Exception):
                    dead.add(conn_id)
            assert len(dead) == 3
            await server.close()
        asyncio.run(scenario())


class TestServerClose:
    def test_server_close_flushes(self):
        """Server Close flushes its pending writes to every client
        (ref TestServerClose)."""
        async def scenario():
            params = params_with(window=2, backoff=1, epoch_ms=50, limit=30)
            server = await new_async_server(0, params)
            client = await new_async_client(f"127.0.0.1:{server.port}", params)
            client.write(b"register")
            conn_id, _ = await asyncio.wait_for(server.read(), 5)
            n = 8
            for i in range(n):
                server.write(conn_id, f"s{i}".encode())
            await server.close()  # blocks until flushed
            got = [await asyncio.wait_for(client.read(), 5) for _ in range(n)]
            assert got == [f"s{i}".encode() for i in range(n)]
            await client.close()
        asyncio.run(scenario())

    def test_close_conn_nonblocking_and_client_times_out(self):
        """CloseConn returns immediately; the client later sees loss
        (ref TestServerCloseConns)."""
        async def scenario():
            params = params_with(epoch_ms=40, limit=4)
            server = await new_async_server(0, params)
            client = await new_async_client(f"127.0.0.1:{server.port}", params)
            client.write(b"x")
            conn_id, _ = await asyncio.wait_for(server.read(), 5)
            server.close_conn(conn_id)
            with pytest.raises(ConnectionClosed):
                server.write(conn_id, b"after close")
            # The closed server conn stops heartbeating; client times out.
            with pytest.raises((ConnectionLost, ConnectionClosed)):
                while True:
                    await asyncio.wait_for(client.read(), 5)
            await client.close()
            await server.close()
        asyncio.run(scenario())


class TestLossDetection:
    def test_client_detects_dead_server(self):
        async def scenario():
            params = params_with(epoch_ms=40, limit=4)
            server = await new_async_server(0, params)
            client = await new_async_client(f"127.0.0.1:{server.port}", params)
            await server.close()
            with pytest.raises((ConnectionLost, ConnectionClosed)):
                while True:
                    await asyncio.wait_for(client.read(), 5)
            await client.close()
        asyncio.run(scenario())

    def test_write_after_loss_raises(self):
        async def scenario():
            params = params_with(epoch_ms=40, limit=3)
            server = await new_async_server(0, params)
            client = await new_async_client(f"127.0.0.1:{server.port}", params)
            await server.close()
            await asyncio.sleep(0.4)  # > epoch_limit epochs
            with pytest.raises(LspError):
                client.write(b"into the void")
            await client.close()
        asyncio.run(scenario())
