"""Process-level CLI conformance: server/miner/client as three OS processes.

The analog of BASELINE config 1 (stock harness run): the reference CLI
contracts are ``server <port>``, ``miner <host:port>``,
``client <host:port> <message> <maxNonce>`` (ref: p1/README.md:110-135), with
client stdout ``Result <hash> <nonce>`` or ``Disconnected``.
"""

import os
import socket
import subprocess
import sys
import time

from distributed_bitcoinminer_tpu.bitcoin.hash import scan_min

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _free_port():
    with socket.socket(socket.AF_INET, socket.SOCK_DGRAM) as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _spawn(args, cwd, extra_env=None):
    env = dict(os.environ, PYTHONPATH=_REPO, DBM_COMPUTE="host")
    env.update(extra_env or {})
    return subprocess.Popen(
        [sys.executable, "-m", *args], cwd=cwd, env=env, stdin=subprocess.PIPE,
        stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True)


def test_three_process_round_trip(tmp_path):
    port = _free_port()
    pkg = "distributed_bitcoinminer_tpu.apps"
    server = _spawn([f"{pkg}.server", str(port)], tmp_path)
    miner = client = None
    try:
        time.sleep(1.0)  # server bind + listen
        miner = _spawn([f"{pkg}.miner", f"127.0.0.1:{port}"], tmp_path)
        time.sleep(1.0)  # miner join
        client = _spawn(
            [f"{pkg}.client", f"127.0.0.1:{port}", "cmu440", "999"], tmp_path)
        out, err = client.communicate(timeout=60)
        want_hash, want_nonce = scan_min("cmu440", 0, 1000)  # +1 ref quirk
        assert out.strip() == f"Result {want_hash} {want_nonce}", (out, err)
    finally:
        for proc in (client, miner, server):
            if proc is not None:
                proc.kill()
                proc.wait()


def test_srunner_crunner_echo(tmp_path):
    """Echo runners interoperate process-to-process with reference flags
    (ref: srunner.go:15-24, crunner.go:16-26), including a drop rate."""
    port = _free_port()
    pkg = "distributed_bitcoinminer_tpu.runners"
    # elim 15 (not the default 5): with 15% drops AND a loaded 1-core CI
    # box, 100 ms epochs slip — 5 silent epochs once flaked a spec-legal
    # connection loss mid-test (round 5). The flags under test are
    # unaffected.
    srv = _spawn([f"{pkg}.srunner", "--port", str(port), "--ems", "100",
                  "--wsize", "4", "--elim", "15"], tmp_path)
    cli = None
    try:
        time.sleep(1.0)
        cli = _spawn([f"{pkg}.crunner", "--port", str(port), "--ems", "100",
                      "--wsize", "4", "--wdrop", "15", "--maxbackoff", "2",
                      "--elim", "15"], tmp_path)
        out, err = cli.communicate("hello echo world\n", timeout=45)
        assert out.count("Server: ") == 3, (out, err)
        assert "Server: hello" in out and "Server: world" in out
    finally:
        for proc in (cli, srv):
            if proc is not None:
                proc.kill()
                proc.wait()


def test_runners_accept_go_flag_spellings(tmp_path):
    """A shell driver written for the stock Go harness uses Go `flag`
    spellings (`-port=9999`, `-v`, srunner.go:15-24); the runners must
    accept them unmodified (VERDICT r3 missing #1 tail)."""
    port = _free_port()
    pkg = "distributed_bitcoinminer_tpu.runners"
    srv = _spawn([f"{pkg}.srunner", f"-port={port}", "-ems=100",
                  "-wsize=4"], tmp_path)
    cli = None
    try:
        time.sleep(1.0)
        cli = _spawn([f"{pkg}.crunner", f"-port={port}", "-ems", "100",
                      "-wsize=4", "-v"], tmp_path)
        out, err = cli.communicate("go flags\n", timeout=45)
        assert out.count("Server: ") == 2, (out, err)
        assert "Server: go" in out and "Server: flags" in out
    finally:
        for proc in (cli, srv):
            if proc is not None:
                proc.kill()
                proc.wait()


def test_normalize_go_flags_rewrites_only_known_long_options():
    from distributed_bitcoinminer_tpu.runners.srunner import (
        build_parser, normalize_go_flags)
    parser = build_parser("srunner")
    assert normalize_go_flags(["-port=9", "-v", "-ems", "50"], parser) == \
        ["--port=9", "-v", "--ems", "50"]
    # Unknown single-dash names, values, and post-`--` tokens untouched.
    assert normalize_go_flags(["-nope=1", "-5", "--", "-port=9"], parser) == \
        ["-nope=1", "-5", "--", "-port=9"]
    args = parser.parse_args(normalize_go_flags(
        ["-port=1234", "-wsize=4", "-v"], parser))
    assert (args.port, args.wsize, args.v) == (1234, 4, True)


def test_client_usage_errors(tmp_path):
    pkg = "distributed_bitcoinminer_tpu.apps"
    bad = _spawn([f"{pkg}.client", "127.0.0.1:1", "msg", "notanumber"], tmp_path)
    out, _ = bad.communicate(timeout=30)
    assert "notanumber is not a number." in out


class TestMinerProbePin:
    """The CLI miner must not inherit a hang from a dead accelerator
    tunnel (round 5: bare miners wedged in axon backend init for the
    whole session): a failed deadlined probe pins the process to CPU."""

    def _pin(self):
        from distributed_bitcoinminer_tpu.apps.miner import (
            _pin_platform_if_backend_wedged)
        return _pin_platform_if_backend_wedged

    def test_failed_probe_pins_cpu(self, monkeypatch):
        from distributed_bitcoinminer_tpu.utils import config
        monkeypatch.setenv("JAX_PLATFORMS", "axon")  # the ambient pin
        monkeypatch.delenv("DBM_COORDINATOR", raising=False)
        monkeypatch.delenv("DBM_MINER_PROBE_TIMEOUT_S", raising=False)
        monkeypatch.setattr(
            config, "probe_backend",
            lambda t: {"error": "backend init exceeded deadline"})
        assert self._pin()() is True   # True = CPU pin applied here
        import os
        assert os.environ["JAX_PLATFORMS"] == "cpu"

    def test_healthy_probe_keeps_platform(self, monkeypatch):
        from distributed_bitcoinminer_tpu.utils import config
        monkeypatch.setenv("JAX_PLATFORMS", "axon")
        monkeypatch.delenv("DBM_COORDINATOR", raising=False)
        monkeypatch.delenv("DBM_MINER_PROBE_TIMEOUT_S", raising=False)
        monkeypatch.setattr(config, "probe_backend",
                            lambda t: {"platform": "tpu", "n": 1})
        assert self._pin()() is False
        import os
        assert os.environ["JAX_PLATFORMS"] == "axon"

    def test_probe_skipped_for_cpu_pin_and_pod_mode(self, monkeypatch):
        from distributed_bitcoinminer_tpu.utils import config

        def boom(t):
            raise AssertionError("probe must not run")
        monkeypatch.setattr(config, "probe_backend", boom)
        monkeypatch.setenv("JAX_PLATFORMS", "cpu")
        monkeypatch.delenv("DBM_COORDINATOR", raising=False)
        assert self._pin()() is False
        monkeypatch.setenv("JAX_PLATFORMS", "axon")
        monkeypatch.setenv("DBM_COORDINATOR", "h0:1234")
        self._pin()()
        monkeypatch.delenv("DBM_COORDINATOR")
        monkeypatch.setenv("DBM_MINER_PROBE_TIMEOUT_S", "0")
        self._pin()()
        monkeypatch.delenv("DBM_MINER_PROBE_TIMEOUT_S")
        self._pin()("host")  # native tier never touches a JAX backend

    def test_cpu_fallback_config_upgrades_only_auto(self, monkeypatch):
        from distributed_bitcoinminer_tpu import native
        from distributed_bitcoinminer_tpu.apps.miner import (
            _cpu_fallback_config)
        from distributed_bitcoinminer_tpu.utils.config import FrameworkConfig
        monkeypatch.setattr(native, "available", lambda: True)
        assert _cpu_fallback_config(
            FrameworkConfig(compute="auto")).compute == "host"
        # Explicit pins are respected; no native toolchain = no upgrade.
        assert _cpu_fallback_config(
            FrameworkConfig(compute="jnp")).compute == "jnp"
        monkeypatch.setattr(native, "available", lambda: False)
        assert _cpu_fallback_config(
            FrameworkConfig(compute="auto")).compute == "auto"
