"""Deterministic scheduler-level tests of the drop-recovery state machine.

The chaos suite (test_chaos.py) exercises these paths over real UDP with
real timing; here the same transitions are driven synchronously through the
Scheduler's event handlers against a recording fake server, so each
interleaving — parked-chunk absorption on join and on free, stale-Result
pop ordering, client-drop racing a miner drop, lease expiry bookkeeping —
is pinned exactly, with no sleeps and no races.

These are the recovery paths the lease machinery extends (ISSUE 1 satellite):
regressions here historically hid behind timing luck in the e2e tests.
"""

from distributed_bitcoinminer_tpu.apps.scheduler import (Chunk, Request,
                                                         ResultCache,
                                                         Scheduler)
from distributed_bitcoinminer_tpu.bitcoin.hash import MAX_U64
from distributed_bitcoinminer_tpu.bitcoin.message import (
    Message, MsgType, new_join, new_request, new_result)
from distributed_bitcoinminer_tpu.utils.config import (CacheParams,
                                                       LeaseParams,
                                                       VerifyParams)


class FakeServer:
    """Records every write; the scheduler never reads from it directly."""

    def __init__(self):
        self.writes = []   # (conn_id, Message)

    def write(self, conn_id, payload):
        self.writes.append((conn_id, Message.from_json(payload)))

    def sent_to(self, conn_id, mtype=None):
        return [m for c, m in self.writes
                if c == conn_id and (mtype is None or m.type == mtype)]


def make_scheduler(**lease_kw):
    # The scripted result() helper answers with synthetic hashes the
    # claim check would reject; verification has its own suite
    # (test_verify.py), so this rig pins it off.
    lease = LeaseParams(**lease_kw) if lease_kw else LeaseParams()
    server = FakeServer()
    return Scheduler(server, lease=lease,
                     verify=VerifyParams(enabled=False)), server


def join(sched, conn_id):
    sched._on_join(conn_id)


def request(sched, conn_id, data, max_nonce, target=0):
    sched._on_request(conn_id, new_request(data, 0, max_nonce, target))


def result(sched, conn_id, h=1, nonce=0, target=0):
    sched._on_result(conn_id, new_result(h, nonce, target))


MINER_A, MINER_B, MINER_C = 1, 2, 3
CLIENT_X, CLIENT_Y = 10, 11


def test_parked_chunk_absorbed_on_join():
    sched, server = make_scheduler()
    join(sched, MINER_A)
    request(sched, CLIENT_X, "park me", 99)
    assert len(server.sent_to(MINER_A, MsgType.REQUEST)) == 1
    sched._on_drop(MINER_A)            # no spare: the chunk parks
    assert len(sched.parked) == 1
    join(sched, MINER_B)               # joiner absorbs it immediately
    assert sched.parked == []
    reqs = server.sent_to(MINER_B, MsgType.REQUEST)
    assert len(reqs) == 1
    assert (reqs[0].lower, reqs[0].upper) == (0, 100)
    result(sched, MINER_B)
    assert len(server.sent_to(CLIENT_X, MsgType.RESULT)) == 1


def test_parked_chunk_absorbed_on_free():
    sched, server = make_scheduler()
    join(sched, MINER_A)
    join(sched, MINER_B)
    request(sched, CLIENT_X, "two chunks", 199)
    assert sched.current.num_chunks == 2
    sched._on_drop(MINER_B)            # A is busy -> B's chunk parks
    assert len(sched.parked) == 1
    result(sched, MINER_A)             # A frees and must absorb the park
    assert sched.parked == []
    reqs = server.sent_to(MINER_A, MsgType.REQUEST)
    assert len(reqs) == 2              # its own chunk + the rescued one
    result(sched, MINER_A)             # answers the rescued chunk
    assert len(server.sent_to(CLIENT_X, MsgType.RESULT)) == 1
    assert all(m.available for m in sched.miners)


def test_stale_result_pops_in_fifo_order():
    """A cancelled chunk still occupies its slot in the miner's pending
    FIFO: the miner answers sequentially, so the first Result after a
    cancellation answers the CANCELLED chunk (dropped as stale) and only
    the next one answers the live assignment."""
    sched, server = make_scheduler()
    join(sched, MINER_A)
    request(sched, CLIENT_X, "doomed", 99)
    sched._on_drop(CLIENT_X)           # client gone: chunk cancelled
    old_chunk = sched.miners[0].pending[0]
    assert old_chunk.cancelled and sched.current is None
    request(sched, CLIENT_Y, "live", 199)
    assert [c.data for c in sched.miners[0].pending] == ["doomed", "live"]
    result(sched, MINER_A, h=7, nonce=3)   # answers "doomed": stale, dropped
    assert server.sent_to(CLIENT_Y) == []
    assert [c.data for c in sched.miners[0].pending] == ["live"]
    result(sched, MINER_A, h=9, nonce=5)   # answers "live": released
    replies = server.sent_to(CLIENT_Y, MsgType.RESULT)
    assert [(m.hash, m.nonce) for m in replies] == [(9, 5)]
    assert sched.miners[0].pending == []


def test_client_drop_then_miner_drop_does_not_resurrect_chunks():
    sched, server = make_scheduler()
    join(sched, MINER_A)
    join(sched, MINER_B)
    request(sched, CLIENT_X, "racing", 199)
    sched._on_drop(CLIENT_X)           # cancel first
    assert sched.current is None
    assert all(c.cancelled for m in sched.miners for c in m.pending)
    sched._on_drop(MINER_A)            # then the miner dies
    # Its cancelled chunk must NOT be reassigned or parked.
    assert sched.parked == []
    assert len(server.sent_to(MINER_B, MsgType.REQUEST)) == 1  # only its own
    request(sched, CLIENT_Y, "fresh", 99)   # pool still serves
    result(sched, MINER_B)                  # stale pop for "racing"
    result(sched, MINER_B)                  # answers "fresh"
    assert len(server.sent_to(CLIENT_Y, MsgType.RESULT)) == 1


def test_miner_drop_then_client_drop_clears_parked():
    sched, server = make_scheduler()
    join(sched, MINER_A)
    join(sched, MINER_B)
    request(sched, CLIENT_X, "racing", 199)
    sched._on_drop(MINER_B)            # A busy -> B's chunk parks
    assert len(sched.parked) == 1
    sched._on_drop(CLIENT_X)           # the requester dies too
    assert sched.parked == []          # parked work of a dead client: gone
    assert sched.current is None
    request(sched, CLIENT_Y, "fresh", 99)
    result(sched, MINER_A)             # stale pop for "racing"
    result(sched, MINER_A)             # answers "fresh"
    assert len(server.sent_to(CLIENT_Y, MsgType.RESULT)) == 1


def test_lease_expiry_reissues_and_quarantines():
    """Unit-level lease sweep: expiry re-issues to an eligible miner once,
    repeat offenses quarantine, and the answer lifts the quarantine
    (timing-free complement to the chaos e2e)."""
    sched, server = make_scheduler(grace_s=30.0, quarantine_after=1,
                                   floor_s=0.1, factor=4.0, tick_s=0.01)
    join(sched, MINER_A)
    join(sched, MINER_B)
    join(sched, MINER_C)
    request(sched, CLIENT_X, "slow pool", 299)
    assert sched.current.num_chunks == 3
    a = sched._find_miner(MINER_A)
    stuck = a.pending[0]
    result(sched, MINER_C)             # C frees: an eligible takeover
    stuck.deadline = 0.0               # force A's lease into the past
    sched._check_leases()
    assert sched.stats["leases_blown"] == 1
    assert sched.stats["reissues"] == 1
    assert stuck.lease_blown and stuck.reissued
    assert a.quarantined               # quarantine_after=1
    copies = [m for m in server.sent_to(MINER_C, MsgType.REQUEST)
              if (m.lower, m.upper) == (stuck.lower, stuck.upper)]
    assert len(copies) == 1            # C's own chunk + exactly one copy
    # A second sweep must not double-issue the same chunk.
    sched._check_leases()
    assert sched.stats["reissues"] == 1
    # First Result wins; the request completes without A.
    result(sched, MINER_B)
    result(sched, MINER_C)
    assert len(server.sent_to(CLIENT_X, MsgType.RESULT)) == 1
    # Retire cancelled A's stale copy, so A is available but quarantined:
    # it gets no part of the next request.
    assert a.available and a.quarantined
    request(sched, CLIENT_Y, "without A", 199)
    assert sched.current.num_chunks == 2
    assert len(server.sent_to(MINER_A, MsgType.REQUEST)) == 1  # nothing new
    # A's eventual stale answer lifts the quarantine.
    result(sched, MINER_A)
    assert not a.quarantined and a.blown_streak == 0
    result(sched, MINER_B)
    result(sched, MINER_C)
    assert len(server.sent_to(CLIENT_Y, MsgType.RESULT)) == 1


def test_duplicate_result_in_flight_is_dropped_and_counted():
    """The speculation loser answers while the job is STILL in flight
    (another chunk unanswered): merged idx pops as a duplicate, the client
    sees exactly one Result at the barrier."""
    sched, server = make_scheduler()
    join(sched, MINER_A)
    join(sched, MINER_B)
    join(sched, MINER_C)
    request(sched, CLIENT_X, "dup race", 299)
    a = sched._find_miner(MINER_A)
    stuck = a.pending[0]
    result(sched, MINER_C, h=50, nonce=7)  # C frees (its chunk answered)
    stuck.deadline = 0.0
    sched._check_leases()                  # re-issue A's chunk to C
    assert sched.stats["reissues"] == 1
    result(sched, MINER_C, h=40, nonce=2)  # the COPY answers chunk 0
    assert sched.current.answered[stuck.idx]
    result(sched, MINER_A, h=40, nonce=2)  # the loser answers: duplicate
    assert sched.stats["dup_results"] == 1
    assert server.sent_to(CLIENT_X, MsgType.RESULT) == []  # barrier holds
    result(sched, MINER_B, h=60, nonce=9)  # last live chunk
    replies = server.sent_to(CLIENT_X, MsgType.RESULT)
    assert [(m.hash, m.nonce) for m in replies] == [(40, 2)]


def test_empty_range_burst_drains_iteratively():
    """Regression: each empty-range request finishes inside its own
    dispatch, so a burst of them must drain through _maybe_dispatch's
    re-entrancy guard iteratively — not one recursion frame set per
    request (a ~250-deep burst used to overflow the stack and kill the
    scheduler actor)."""
    from distributed_bitcoinminer_tpu.utils.config import QosParams
    server = FakeServer()
    # Unbounded intake (max_queued=0): this pins the re-entrancy guard,
    # not the ISSUE 5 overload shed — which would (correctly) cut a
    # 2000-deep same-conn burst down to DBM_QOS_MAX_QUEUED first.
    sched = Scheduler(server, lease=LeaseParams(),
                      qos=QosParams(max_queued=0),
                      verify=VerifyParams(enabled=False))
    join(sched, MINER_A)
    bad = Message(type=MsgType.REQUEST, data="void", lower=5, upper=3)
    for _ in range(2000):
        # Queue ownership moved to the tenant plane (ISSUE 11 split);
        # enqueue() is the supported direct-injection surface.
        sched.tenant_plane.enqueue(Request(conn_id=CLIENT_X, data="void",
                                           lower=5, upper=3))
    sched._on_request(CLIENT_X, bad)   # triggers the drain
    replies = server.sent_to(CLIENT_X, MsgType.RESULT)
    assert len(replies) == 2001
    assert all((m.hash, m.nonce) == (MAX_U64, 0) for m in replies)
    assert sched.queue == [] and sched.current is None


def test_empty_range_still_answers_with_quarantined_miner_present():
    """_load_balance must split over ELIGIBLE miners only; a quarantined
    straggler neither blocks dispatch nor receives work."""
    sched, server = make_scheduler()
    join(sched, MINER_A)
    join(sched, MINER_B)
    sched._find_miner(MINER_A).quarantined = True
    request(sched, CLIENT_X, "one lane", 99)
    assert sched.current.num_chunks == 1
    assert server.sent_to(MINER_A, MsgType.REQUEST) == []
    bad = Message(type=MsgType.REQUEST, data="void", lower=5, upper=3)
    sched._on_request(CLIENT_Y, bad)       # queued behind the live job
    result(sched, MINER_B)
    replies = server.sent_to(CLIENT_Y, MsgType.RESULT)
    assert [(m.hash, m.nonce) for m in replies] == [(MAX_U64, 0)]


# --------------------------------------------------- result memoization plane


def test_result_cache_replays_identical_request_without_pool():
    """The retry-path satellite: a resubmitted (data, lower, upper,
    target) request after a lost Result replays in O(1) from the memo —
    no new chunk is dispatched, the recorded answer is returned as-is."""
    sched, server = make_scheduler()
    join(sched, MINER_A)
    request(sched, CLIENT_X, "memo", 99)
    result(sched, MINER_A, h=5, nonce=2)
    assert sched.stats["cache_stores"] == 1
    assert len(server.sent_to(CLIENT_X, MsgType.RESULT)) == 1
    request(sched, CLIENT_Y, "memo", 99)     # identical key, other client
    assert len(server.sent_to(MINER_A, MsgType.REQUEST)) == 1  # no new work
    replies = server.sent_to(CLIENT_Y, MsgType.RESULT)
    assert [(m.hash, m.nonce) for m in replies] == [(5, 2)]
    assert sched.stats["cache_hits"] == 1
    assert sched.stats["results_sent"] == 2
    assert sched.queue == [] and sched.current is None


def test_result_cache_keys_on_full_request_identity():
    """Different bounds or a different target are different searches: no
    false sharing across the (data, lower, upper, target) key."""
    sched, server = make_scheduler()
    join(sched, MINER_A)
    request(sched, CLIENT_X, "keyed", 99)
    result(sched, MINER_A, h=5, nonce=2)
    request(sched, CLIENT_X, "keyed", 199)         # wider range: miss
    assert sched.stats["cache_hits"] == 0
    assert len(server.sent_to(MINER_A, MsgType.REQUEST)) == 2
    result(sched, MINER_A, h=4, nonce=150)
    request(sched, CLIENT_X, "keyed", 99, target=1 << 60)  # target: miss
    assert sched.stats["cache_hits"] == 0
    assert len(server.sent_to(MINER_A, MsgType.REQUEST)) == 3


def test_result_cache_lru_bound_evicts_oldest():
    cache = ResultCache(2)
    cache.put(("a", 0, 1, 0), (1, 1))
    cache.put(("b", 0, 1, 0), (2, 2))
    cache.put(("a", 0, 1, 0), (1, 1))      # refresh "a": now newest
    cache.put(("c", 0, 1, 0), (3, 3))      # evicts "b", not "a"
    assert len(cache) == 2
    assert cache.get(("a", 0, 1, 0)) == (1, 1)
    assert cache.get(("b", 0, 1, 0)) is None
    assert cache.get(("c", 0, 1, 0)) == (3, 3)


def test_weak_difficulty_merge_is_not_cached():
    """A stock miner answering a target chunk weakens the merge to 'a
    qualifying nonce' — not a deterministic function of the key, so it
    must never be memoized (a replay could contradict a re-run)."""
    sched, server = make_scheduler()
    join(sched, MINER_A)
    request(sched, CLIENT_X, "weak", 99, target=1 << 60)
    # Miner drops the Target key (stock shape): echo target=0.
    result(sched, MINER_A, h=5, nonce=2, target=0)
    assert len(server.sent_to(CLIENT_X, MsgType.RESULT)) == 1
    assert sched.stats["cache_stores"] == 0
    request(sched, CLIENT_Y, "weak", 99, target=1 << 60)
    assert sched.stats["cache_hits"] == 0  # re-runs the search


def test_cache_disabled_knob():
    sched = Scheduler(FakeServer(), cache=CacheParams(enabled=False),
                      verify=VerifyParams(enabled=False))
    assert sched.results is None
    join(sched, MINER_A)
    request(sched, CLIENT_X, "off", 99)
    result(sched, MINER_A, h=5, nonce=2)
    assert sched.stats["cache_stores"] == 0
    request(sched, CLIENT_Y, "off", 99)
    assert sched.stats["cache_hits"] == 0
    assert len(sched.server.sent_to(MINER_A, MsgType.REQUEST)) == 2


# ------------------------------------------- queue-age + starvation telemetry


def test_no_eligible_miner_latches_once_per_episode():
    """A dispatch pass that finds queued work but an empty (or fully
    quarantined-and-busy) pool must say so — once per starvation episode,
    not per event — and clear when the pool recovers. (A fully quarantined
    pool with an AVAILABLE miner no longer starves: desperation dispatch
    takes over — see the dedicated tests below.)"""
    sched, server = make_scheduler()
    request(sched, CLIENT_X, "starved", 99)        # no miners at all
    assert sched.stats["no_eligible_miner"] == 1
    request(sched, CLIENT_Y, "also starved", 99)   # same episode
    assert sched.stats["no_eligible_miner"] == 1
    join(sched, MINER_A)                           # pool recovers
    assert sched.current is not None
    result(sched, MINER_A)
    result(sched, MINER_A)
    assert len(server.sent_to(CLIENT_X, MsgType.RESULT)) == 1
    assert len(server.sent_to(CLIENT_Y, MsgType.RESULT)) == 1
    # A fresh starvation episode: the whole pool is quarantined AND busy
    # (a live chunk still pending), so even desperation has no taker.
    a = sched._find_miner(MINER_A)
    a.quarantined = True
    a.pending.append(Chunk(job_id=999, data="wedged", lower=0, upper=9))
    request(sched, CLIENT_X, "starved again", 99)
    assert sched.stats["no_eligible_miner"] == 2
    assert sched.stats["desperation_dispatch"] == 0


def test_queue_age_alarm_fires_once_per_bound_interval():
    sched, _server = make_scheduler(queue_alarm_s=5.0)
    request(sched, CLIENT_X, "stalled", 99)        # no miners: stays queued
    req = sched.queue[0]
    sched._check_queue_age()                       # too young: silent
    assert sched.stats["queue_alarms"] == 0
    req.queued_at -= 100.0                         # age it past the bound
    sched._check_queue_age()
    assert sched.stats["queue_alarms"] == 1
    sched._check_queue_age()                       # within re-warn window
    assert sched.stats["queue_alarms"] == 1
    req.last_alarm -= 100.0                        # next interval elapsed
    sched._check_queue_age()
    assert sched.stats["queue_alarms"] == 2
    join(sched, MINER_A)                           # dispatches; queue empty
    sched._check_queue_age()
    assert sched.stats["queue_alarms"] == 2


def test_result_cache_replays_at_dispatch_time_too():
    """A duplicate that queued BEHIND its still-in-flight original (the
    common retry race) must replay from the memo when it is POPPED, not
    re-run the whole search: the original finished and stored while the
    duplicate waited in the queue."""
    sched, server = make_scheduler()
    join(sched, MINER_A)
    request(sched, CLIENT_X, "dup race", 99)   # in flight
    request(sched, CLIENT_Y, "dup race", 99)   # queued; cache still empty
    assert len(sched.queue) == 1 and sched.stats["cache_hits"] == 0
    result(sched, MINER_A, h=5, nonce=2)       # finishes + stores + pops queue
    replies = server.sent_to(CLIENT_Y, MsgType.RESULT)
    assert [(m.hash, m.nonce) for m in replies] == [(5, 2)]
    assert sched.stats["cache_hits"] == 1
    assert len(server.sent_to(MINER_A, MsgType.REQUEST)) == 1  # no re-run
    assert sched.queue == [] and sched.current is None


# ------------------------------------------------- ISSUE 3 scheduling planes


def test_desperation_dispatch_to_least_bad_quarantined():
    """When the ENTIRE pool is quarantined, a queued request goes to the
    least-bad available quarantined miner (lowest blown streak) as a last
    resort instead of stalling forever (ROADMAP open item)."""
    sched, server = make_scheduler()
    join(sched, MINER_A)
    join(sched, MINER_B)
    a, b = sched._find_miner(MINER_A), sched._find_miner(MINER_B)
    a.quarantined, a.blown_streak = True, 5
    b.quarantined, b.blown_streak = True, 2
    request(sched, CLIENT_X, "last resort", 99)
    assert sched.stats["desperation_dispatch"] == 1
    assert sched.stats["no_eligible_miner"] == 0
    assert sched.current is not None and sched.current.num_chunks == 1
    # Only the least-bad miner (B: shorter blown streak) got the work.
    assert server.sent_to(MINER_A, MsgType.REQUEST) == []
    assert len(server.sent_to(MINER_B, MsgType.REQUEST)) == 1
    result(sched, MINER_B, h=5, nonce=2)       # answer lifts B's quarantine
    assert not b.quarantined
    replies = server.sent_to(CLIENT_X, MsgType.RESULT)
    assert [(m.hash, m.nonce) for m in replies] == [(5, 2)]


def test_desperation_disabled_knob_keeps_starvation_latch():
    sched, server = make_scheduler(desperation=False)
    join(sched, MINER_A)
    sched._find_miner(MINER_A).quarantined = True
    request(sched, CLIENT_X, "still starved", 99)
    assert sched.stats["desperation_dispatch"] == 0
    assert sched.stats["no_eligible_miner"] == 1
    assert sched.current is None and len(sched.queue) == 1
    assert server.sent_to(MINER_A, MsgType.REQUEST) == []


def test_desperation_requires_whole_pool_quarantined():
    """A single healthy-but-busy miner disables desperation: waiting for
    it to free beats feeding a known-bad quarantined miner."""
    sched, server = make_scheduler()
    join(sched, MINER_A)
    join(sched, MINER_B)
    sched._find_miner(MINER_A).quarantined = True
    b = sched._find_miner(MINER_B)
    b.pending.append(Chunk(job_id=999, data="busy", lower=0, upper=9))
    request(sched, CLIENT_X, "patience", 99)
    assert sched.stats["desperation_dispatch"] == 0
    assert sched.current is None and len(sched.queue) == 1
    assert server.sent_to(MINER_A, MsgType.REQUEST) == []


def test_fifo_aware_lease_budgets_predecessors_then_tightens_at_head():
    """Position-aware deadline (ROADMAP open item): a chunk assigned
    BEHIND a cancelled-but-still-computing FIFO entry gets a deadline
    budgeting the predecessor's remaining lease plus its own — no
    spurious blow while the miner grinds the entry ahead — and the clock
    re-stamps to the tight single-chunk lease when it reaches the head."""
    sched, server = make_scheduler()
    join(sched, MINER_A)
    request(sched, CLIENT_X, "doomed", 99)
    ahead = sched.miners[0].pending[0]
    sched._on_drop(CLIENT_X)               # cancelled; A still grinding it
    request(sched, CLIENT_Y, "queued behind", 199)
    live = sched.miners[0].pending[1]
    # Budgeted, not started: expiry covers the predecessor's lease too.
    assert not live.lease_started
    assert live.deadline > ahead.deadline
    sched._check_leases()                  # inside the budget: no blow
    assert sched.stats["leases_blown"] == 0
    assert sched.stats["leases_blown_spurious"] == 0
    result(sched, MINER_A)                 # stale pop: A reaches the chunk
    assert live.lease_started and live.deadline > 0.0
    result(sched, MINER_A, h=9, nonce=5)   # answers the live chunk
    replies = server.sent_to(CLIENT_Y, MsgType.RESULT)
    assert [(m.hash, m.nonce) for m in replies] == [(9, 5)]
    t = sched.trace(2)
    assert t is not None and t.closed


def test_fifo_aware_wedged_head_still_expires_deep_chunk():
    """The budget must RUN OUT when the FIFO head is wedged — a deferred
    chunk is never exempt from speculation forever (the flaw a pure
    start-at-head clock would have)."""
    sched, server = make_scheduler()
    join(sched, MINER_A)
    request(sched, CLIENT_X, "doomed", 99)
    sched._on_drop(CLIENT_X)               # A grinding a cancelled entry
    join(sched, MINER_B)                   # B joins clean
    request(sched, CLIENT_Y, "stuck deep", 199)
    live = next(c for c in sched.miners[0].pending if not c.cancelled)
    result(sched, MINER_B)                 # B frees: an eligible takeover
    live.deadline = 0.0                    # the whole budget elapsed
    sched._check_leases()
    assert sched.stats["leases_blown"] == 1
    assert sched.stats["leases_blown_spurious"] == 0   # justified, not noise
    assert sched.stats["reissues"] == 1    # rescued despite never starting
    result(sched, MINER_B, h=3, nonce=1)   # the re-issued copy answers
    assert len(server.sent_to(CLIENT_Y, MsgType.RESULT)) == 1


def test_at_assignment_clock_blows_spuriously_and_is_counted():
    """The pre-fix behavior (fifo_aware=False) is preserved behind the
    knob, and its failure mode — a lease blowing while the miner had not
    even reached the chunk — is counted in ``leases_blown_spurious``: the
    before/after evidence for the position-aware fix."""
    sched, server = make_scheduler(fifo_aware=False)
    join(sched, MINER_A)
    request(sched, CLIENT_X, "doomed", 99)
    sched._on_drop(CLIENT_X)
    request(sched, CLIENT_Y, "queued behind", 199)
    live = sched.miners[0].pending[1]
    assert live.lease_started               # old behavior: clock at assign
    live.deadline = 0.0                     # force expiry while queued deep
    sched._check_leases()
    assert sched.stats["leases_blown"] == 1
    assert sched.stats["leases_blown_spurious"] == 1
    # The request still completes (speculation is idempotent; answering
    # resets the streak) — the spurious blow was noise, now measured.
    result(sched, MINER_A)
    result(sched, MINER_A, h=9, nonce=5)
    assert len(server.sent_to(CLIENT_Y, MsgType.RESULT)) == 1


def test_inflight_age_alarm_fires_once_per_interval():
    sched, _server = make_scheduler(queue_alarm_s=5.0)
    join(sched, MINER_A)
    request(sched, CLIENT_X, "wedged in flight", 99)
    curr = sched.current
    sched._check_queue_age()                 # too young: silent
    assert sched.stats["inflight_alarms"] == 0
    curr.started -= 100.0                    # age it past the bound
    sched._check_queue_age()
    assert sched.stats["inflight_alarms"] == 1
    sched._check_queue_age()                 # within the re-warn window
    assert sched.stats["inflight_alarms"] == 1
    curr.last_inflight_alarm -= 100.0
    sched._check_queue_age()
    assert sched.stats["inflight_alarms"] == 2
    # The queue-age stamp is independent: a queue alarm before dispatch
    # must not delay the first in-flight alarm (they use separate stamps).
    assert curr.last_alarm == 0.0
    events = [e["event"] for e in curr.trace.to_dict()["events"]]
    assert events.count("inflight_alarm") == 2
