"""Test configuration.

Forces JAX onto a virtual 8-device CPU mesh BEFORE any jax import, so every
sharding/collective codepath is exercised without TPU hardware (the driver
separately dry-runs the multi-chip path; benches run on the real chip).
"""

import os

os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (_flags + " --xla_force_host_platform_device_count=8").strip()

import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import pytest


@pytest.fixture(autouse=True)
def reset_network_faults():
    """Every test starts and ends with a clean fault-injection state."""
    from distributed_bitcoinminer_tpu import lspnet
    lspnet.reset_all_faults()
    yield
    lspnet.reset_all_faults()
    lspnet.stop_sniff()
