"""Test configuration.

Forces JAX onto a virtual 8-device CPU mesh BEFORE any jax import, so every
sharding/collective codepath is exercised without TPU hardware (the driver
separately dry-runs the multi-chip path; benches run on the real chip).
"""

import os

os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (_flags + " --xla_force_host_platform_device_count=8").strip()

import sys

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO)

# Deep static hoist window OFF for the suite: the rounds-16..47 window
# (DBM_HOIST_DEEP, CPU runtime default ON) straight-lines the whole
# 64-round chain, which XLA:CPU compiles ~2x slower PER SIGNATURE — on the
# tier-1 box that doubled test_hash_kernels (83s -> 160s) and blew the
# 870s budget. The window's bit-exactness and knob plumbing are covered
# explicitly (tests/test_hoist.py::TestDeepStaticWindow opts in via
# deep_window=True / monkeypatched env); everything else only needs the
# cheap-to-compile default window. setdefault: an explicit DBM_HOIST_DEEP
# from the caller still wins.
os.environ.setdefault("DBM_HOIST_DEEP", "0")

# Persistent XLA compilation cache: the SHA-256 search graph is large and
# compiles per (rem, k, nbatches, batch) signature; cache makes re-runs fast.
import jax

# The image's sitecustomize registers the real TPU backend before this file
# runs, overriding JAX_PLATFORMS from the environment — force CPU again at
# the config level so tests always see the 8-device virtual mesh.
jax.config.update("jax_platforms", "cpu")

# Host-keyed cache path: a cache written by a different machine hangs/SIGILLs
# when its AOT artifacts load here (round-2 "unrunnable test file" root
# cause) — see utils/config.host_cache_dir.
from distributed_bitcoinminer_tpu.utils.config import host_cache_dir

jax.config.update("jax_compilation_cache_dir", host_cache_dir(_REPO))
jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)

import atexit

import pytest

# --- exit-hang guard -------------------------------------------------------
# The image's axon/jax stack leaves weakref finalizers that only become due
# at interpreter shutdown; after a Pallas eager-interpret workload they hang
# the process for minutes AFTER pytest has printed its summary (round-2
# VERDICT: "tests/test_pallas.py does not finish in 10 minutes" — the tests
# themselves take ~1 min; the exit did not return). atexit handlers run
# LIFO, so this guard — registered after sitecustomize's — fires first and
# ends the process cleanly once pytest is completely done.
_exit_status = [0]


def pytest_sessionfinish(session, exitstatus):
    _exit_status[0] = int(exitstatus)


@atexit.register
def _fast_exit():
    # os._exit skips later-registered atexit handlers; the only one that
    # matters for tooling is coverage's data save — do it explicitly.
    try:
        import coverage
        cov = coverage.Coverage.current()
        if cov is not None:
            cov.stop()
            cov.save()
    except Exception:
        pass
    sys.stdout.flush()
    sys.stderr.flush()
    os._exit(_exit_status[0])


def pytest_configure(config):
    # The tier-1 gate runs `-m 'not slow'`; register the marker so the
    # full-cross-product hoist sweeps (tests/test_hoist.py) don't warn.
    config.addinivalue_line(
        "markers",
        "slow: excluded from the tier-1 gate; run explicitly with -m slow")


@pytest.fixture(autouse=True)
def reset_network_faults():
    """Every test starts and ends with a clean fault-injection state."""
    from distributed_bitcoinminer_tpu import lspnet
    lspnet.reset_all_faults()
    yield
    lspnet.reset_all_faults()
    lspnet.stop_sniff()


@pytest.fixture(autouse=True)
def no_task_leaks(monkeypatch):
    """The ``-race`` analog (VERDICT r1 task 6 / r2 task 8): no asyncio task
    may outlive its test scenario, mirroring the spec rule that no goroutine
    may outlive Close (p1.pdf §2.2.3-2.2.4; the reference grades 40/44 tests
    under the Go race detector).

    ``asyncio.run`` is wrapped so that after the scenario coroutine returns,
    still-pending tasks get a short settle window (in-flight cancellations
    complete in a tick) and anything still alive is reported as a leak.
    Endpoint engines must therefore be torn down by Close, not by the
    loop-shutdown cancellation that ``asyncio.run`` would otherwise hide.
    """
    import asyncio

    leaks: list[str] = []
    orig_run = asyncio.run

    def checked_run(coro, **kw):
        async def wrapper():
            try:
                return await coro
            finally:
                cur = asyncio.current_task()
                for _ in range(40):
                    pending = [t for t in asyncio.all_tasks()
                               if t is not cur and not t.done()]
                    if not pending:
                        break
                    await asyncio.sleep(0.01)
                else:
                    leaks.extend(repr(t) for t in pending)

        return orig_run(wrapper(), **kw)

    monkeypatch.setattr(asyncio, "run", checked_run)
    yield
    assert not leaks, f"asyncio tasks outlived the scenario: {leaks}"
