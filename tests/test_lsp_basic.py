"""LSP basic correctness: echo round-trips, multi-client, robustness to drops.

Port of the reference lsp1_test.go scenarios (TestBasic1-9, TestSendReceive,
TestRobust): N clients x M messages round-trip in order through an echo
server over real localhost UDP, with and without injected faults.
Fast-epoch params keep wall-clock low, as the reference tests do.
"""

import asyncio

import pytest

from distributed_bitcoinminer_tpu import lspnet
from distributed_bitcoinminer_tpu.lsp import Params
from distributed_bitcoinminer_tpu.lsp.client import new_async_client
from distributed_bitcoinminer_tpu.lsp.errors import LspError
from distributed_bitcoinminer_tpu.lsp.server import new_async_server


def fast_params(window=1, backoff=0, epoch_ms=50, limit=5):
    return Params(epoch_limit=limit, epoch_millis=epoch_ms,
                  window_size=window, max_backoff_interval=backoff)


async def echo_server_loop(server, count_box=None):
    """Echo every payload back to its sender (ref: lsp1_test.go:95-113)."""
    while True:
        try:
            conn_id, item = await server.read()
        except LspError:
            return
        if isinstance(item, Exception):
            continue
        if count_box is not None:
            count_box[0] += 1
        try:
            server.write(conn_id, item)
        except LspError:
            pass


async def run_echo(num_clients, num_msgs, params, timeout=15.0, payload_size=8):
    server = await new_async_server(0, params)
    echo_task = asyncio.create_task(echo_server_loop(server))

    async def one_client(idx):
        client = await new_async_client(f"127.0.0.1:{server.port}", params)
        assert client.conn_id() > 0
        for i in range(num_msgs):
            payload = f"c{idx}m{i}".encode().ljust(payload_size, b".")
            client.write(payload)
            got = await client.read()
            assert got == payload, f"client {idx} msg {i}: {got!r} != {payload!r}"
        await client.close()

    try:
        await asyncio.wait_for(
            asyncio.gather(*(one_client(i) for i in range(num_clients))), timeout)
    finally:
        echo_task.cancel()
        await server.close()


class TestBasic:
    def test_single_client_single_message(self):
        asyncio.run(run_echo(1, 1, fast_params()))

    def test_single_client_many_messages(self):
        asyncio.run(run_echo(1, 50, fast_params(window=10)))

    def test_multiple_clients(self):
        asyncio.run(run_echo(3, 20, fast_params(window=5)))

    def test_window_one(self):
        asyncio.run(run_echo(2, 20, fast_params(window=1)))

    def test_big_window_throughput_budget(self):
        # Analog of TestBasic6: 2 clients x 250 msgs, window 20, bounded time.
        asyncio.run(run_echo(2, 250, fast_params(window=20, epoch_ms=100), timeout=10))

    def test_sendreceive_no_epochs(self):
        # Ref TestSendReceive1-3 (lsp1_test.go:269-288): delivery must not
        # lean on epoch ticks — epochs are ~never (5 s) and the whole
        # 2x6-message exchange must finish before the first could fire.
        import time
        t0 = time.monotonic()
        asyncio.run(run_echo(2, 6, fast_params(window=1, epoch_ms=5000,
                                               limit=3)))
        assert time.monotonic() - t0 < 4.0

    def test_conn_ids_unique(self):
        async def scenario():
            params = fast_params()
            server = await new_async_server(0, params)
            clients = [await new_async_client(f"127.0.0.1:{server.port}", params)
                       for _ in range(5)]
            ids = [c.conn_id() for c in clients]
            assert len(set(ids)) == 5
            for c in clients:
                await c.close()
            await server.close()
        asyncio.run(scenario())


class TestSendReceive:
    def test_server_to_client_stream(self):
        # Server-initiated writes (ref TestSendReceive): huge epochs prove
        # delivery without retransmits.
        async def scenario():
            params = fast_params(window=10, epoch_ms=2000, limit=5)
            server = await new_async_server(0, params)
            client = await new_async_client(f"127.0.0.1:{server.port}", params)
            # Must learn the conn id: client sends one message first.
            client.write(b"hello")
            conn_id, payload = await asyncio.wait_for(server.read(), 5)
            assert payload == b"hello"
            for i in range(20):
                server.write(conn_id, f"msg{i}".encode())
            for i in range(20):
                got = await asyncio.wait_for(client.read(), 5)
                assert got == f"msg{i}".encode()
            await client.close()
            await server.close()
        asyncio.run(scenario())


class TestRobust:
    @pytest.mark.parametrize("drop_side", ["client", "server"])
    def test_echo_with_20_percent_write_drop(self, drop_side):
        async def scenario():
            params = fast_params(window=5, backoff=2, epoch_ms=50, limit=20)
            if drop_side == "client":
                lspnet.set_client_write_drop_percent(20)
            else:
                lspnet.set_server_write_drop_percent(20)
            await run_echo(2, 15, params, timeout=15)
        asyncio.run(scenario())

    def test_echo_with_delays(self):
        async def scenario():
            lspnet.set_delay_message_percent(20)
            params = fast_params(window=5, backoff=1, epoch_ms=200, limit=10)
            await run_echo(2, 10, params, timeout=15)
        asyncio.run(scenario())
