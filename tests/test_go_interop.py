"""LIVE interop against the reference's own Go endpoints (VERDICT r4 #6).

C20's ceiling in this image is hand-scripted byte replay
(test_go_replay.py): the staff binaries are darwin-only and no Go
toolchain is installed. This suite makes the gap SELF-CLOSING: it locates
a `go` toolchain at test time, builds the reference's srunner/crunner
from ``/root/reference/p1/src`` (copied into a writable GOPATH;
GOPATH-mode builds need GO111MODULE=off), and drives real cross-process
interop over localhost UDP (ref: p1/README.md:110-141):

- our client <-> their srunner: the golden-corpus payloads roundtrip and
  every outbound Data datagram we put on the wire is byte-identical to
  ``tests/goldens/wire_transcript.json`` (field order, checksum, base64);
- our server <-> their crunner: the Go client connects, echoes through
  our server, and prints the exact payload.

Without a toolchain every test SKIPS (visibly), and the suite goes live
the day the environment gains `go` — no code changes needed.
"""

import asyncio
import os
import shutil
import subprocess
import sys

import pytest

from distributed_bitcoinminer_tpu.lsp.client import new_async_client
from distributed_bitcoinminer_tpu.lsp.params import Params
from distributed_bitcoinminer_tpu.lsp.server import new_async_server
from tests.test_go_replay import golden_payload, load_golden
from tests.test_multihost import _free_udp_port

GO = shutil.which("go")
REF_SRC = "/root/reference/p1/src/github.com/cmu440"

pytestmark = pytest.mark.skipif(
    GO is None or not os.path.isdir(REF_SRC),
    reason="no `go` toolchain on PATH (the reference source tree is "
           "present) — installing Go alone makes this suite live; "
           "see the module docstring")


@pytest.fixture(scope="module")
def go_bins(tmp_path_factory):
    """Build srunner + crunner from the reference source in a writable
    GOPATH (the reference tree itself is read-only)."""
    gopath = tmp_path_factory.mktemp("gopath")
    dst = gopath / "src" / "github.com" / "cmu440"
    shutil.copytree(REF_SRC, dst,
                    ignore=shutil.ignore_patterns("*.tar", "bin"))
    env = {**os.environ, "GOPATH": str(gopath), "GO111MODULE": "off"}
    bins = {}
    for prog in ("srunner", "crunner"):
        out = gopath / prog
        build = subprocess.run(
            [GO, "build", "-o", str(out), f"github.com/cmu440/{prog}"],
            env=env, cwd=str(gopath), capture_output=True, text=True,
            timeout=300)
        # A present-but-failing toolchain is a finding, not a skip.
        assert build.returncode == 0, \
            f"go build {prog} failed:\n{build.stdout}\n{build.stderr}"
        bins[prog] = str(out)
    return bins


def _golden():
    golden, by_label = load_golden("wire_transcript.json")
    return Params(**golden["params"]), by_label


def test_our_client_against_live_go_srunner(go_bins):
    """Their echo server, our client: golden payloads roundtrip and our
    Data bytes on the wire match the golden corpus byte-for-byte."""
    params, by_label = _golden()
    port = _free_udp_port()
    proc = subprocess.Popen(
        [go_bins["srunner"], f"-port={port}",
         f"-ems={params.epoch_millis}", f"-elim={params.epoch_limit}",
         f"-wsize={params.window_size}",
         f"-maxbackoff={params.max_backoff_interval}"],
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True)
    try:
        assert "waiting for clients" in proc.stdout.readline() + \
            proc.stdout.readline()

        async def scenario():
            client = await new_async_client(f"127.0.0.1:{port}", params)
            sent = []
            real_send = client._ep.send
            client._ep.send = lambda raw, *a: (sent.append(raw),
                                               real_send(raw, *a))[1]
            labels = ("data1", "data2", "data3", "data4")
            payloads = [golden_payload(by_label, lb) for lb in labels]
            for p in payloads:
                client.write(p)
            for p in payloads:
                got = await asyncio.wait_for(client.read(), 10)
                assert got == p          # srunner echoes verbatim
            # Byte-exact wire check: what we actually sent to the live Go
            # process is the golden transcript's bytes (srunner grants the
            # first client conn id 1, like the golden scenario).
            for lb in labels:
                assert by_label[lb] in sent, lb
            await client.close()
        asyncio.run(scenario())
    finally:
        proc.kill()
        proc.wait(timeout=10)


def test_live_go_crunner_against_our_server(go_bins):
    """Their client, our server: crunner reads stdin tokens, sends each
    over LSP, and prints the echo our server returns."""
    params, _ = _golden()

    async def scenario():
        server = await new_async_server(0, params)

        async def echo():
            while True:
                cid, payload = await server.read()
                if isinstance(payload, Exception):
                    continue
                server.write(cid, payload)
        echo_task = asyncio.create_task(echo())
        proc = await asyncio.create_subprocess_exec(
            go_bins["crunner"], f"-port={server.port}",
            f"-ems={params.epoch_millis}", f"-elim={params.epoch_limit}",
            f"-wsize={params.window_size}",
            f"-maxbackoff={params.max_backoff_interval}",
            stdin=asyncio.subprocess.PIPE, stdout=asyncio.subprocess.PIPE,
            stderr=asyncio.subprocess.STDOUT)
        try:
            proc.stdin.write(b"interop-token\n")
            await proc.stdin.drain()
            proc.stdin.close()
            out, _ = await asyncio.wait_for(proc.communicate(), 30)
            text = out.decode()
            assert "Server: interop-token" in text, text
        finally:
            if proc.returncode is None:
                proc.kill()
                await proc.wait()
            echo_task.cancel()
            await server.close()
    asyncio.run(scenario())


if __name__ == "__main__":
    sys.exit(pytest.main([__file__, "-v"]))
