"""Randomized fault soak: sustained mixed load through the whole stack.

The reference grades individual fault scenarios; this soak composes them
— lossy links, a slow miner, a mid-run miner death, a replacement join,
and a client that drops mid-request — over a seeded sequence of stock
and difficulty requests, asserting every completed answer is bit-exact
against the host oracle. The autouse ``no_task_leaks`` fixture
(conftest.py) additionally fails the test if any scenario leaves a live
task behind, which is what makes a soak meaningful as a leak/wedge
detector rather than just a long test.

Seeded RNG: the schedule is deterministic run-to-run; timings are not,
which is the point — the assertions hold under any interleaving.
"""

import asyncio
import random

from distributed_bitcoinminer_tpu import lspnet
from distributed_bitcoinminer_tpu.apps.client import submit, submit_until
from distributed_bitcoinminer_tpu.bitcoin.hash import scan_min, scan_until
from distributed_bitcoinminer_tpu.lsp.client import new_async_client
from distributed_bitcoinminer_tpu.lsp.errors import LspError
from tests.test_apps import Cluster, fast_params
from tests.test_difficulty import until_factory


def test_randomized_fault_soak():
    rng = random.Random(0xDB5)
    params = fast_params(epoch_ms=40, limit=8)

    async def scenario():
        losses = 0

        async def attempt(submit_coro):
            """One request; None = spec-legal connection loss under heavy
            drop (ConnectTimeout in the client's own connect, or a
            mid-request ConnectionLost) — the soak retries the round
            instead of failing, but caps total losses so a wedged stack
            can't hide behind the retry."""
            nonlocal losses
            try:
                got = await asyncio.wait_for(submit_coro, 60)
            except LspError:
                got = None
            if got is None:
                losses += 1
                assert losses <= 4, "too many connection losses for 25% drop"
            return got

        async with Cluster(params) as c:
            await c.start_miner(factory=until_factory())
            await c.start_miner(factory=until_factory(delay=0.05))
            # All miners speak until, so difficulty answers stay
            # globally-first-exact for the whole soak (a stock miner
            # would weaken target rounds to "a qualifying nonce").
            victim = await c.start_miner(factory=until_factory())
            try:
                for round_no in range(18):
                    # Random (bounded) loss on both sides, re-rolled
                    # every round; knobs are process-global, so set and
                    # clear around each request.
                    cdrop = rng.choice((0, 0, 10, 25))
                    sdrop = rng.choice((0, 0, 10, 25))
                    lspnet.set_client_write_drop_percent(cdrop)
                    lspnet.set_server_write_drop_percent(sdrop)
                    data = f"soak {round_no}"
                    max_nonce = rng.randrange(2000, 12000)
                    if round_no == 6:
                        # Kill a miner mid-soak: its chunks must
                        # reassign and later rounds run on a 2-pool.
                        victim.client._conn.abort()
                        victim.client._ep.close()
                    if round_no == 12:
                        # Elasticity: a replacement joins mid-soak.
                        await c.start_miner(factory=until_factory())
                    if round_no == 9:
                        # A client that vanishes mid-request: the
                        # scheduler must cancel and serve the next
                        # request untainted.
                        ghost = await new_async_client(c.hostport, params)
                        ghost.write(
                            b'{"Type":1,"Data":"ghost","Lower":0,'
                            b'"Upper":200000,"Hash":0,"Nonce":0}')
                        await asyncio.sleep(0.1)
                        ghost._conn.abort()
                        ghost._ep.close()
                    if rng.random() < 0.5:
                        target = 1 << rng.choice((58, 59))
                        got = await attempt(submit_until(
                            c.hostport, data, max_nonce, target, params))
                        if got is None:
                            continue
                        want = scan_until(data, 0, max_nonce + 1, target)
                        assert got == want, (round_no, got, want)
                    else:
                        got = await attempt(submit(
                            c.hostport, data, max_nonce, params))
                        if got is None:
                            continue
                        want = scan_min(data, 0, max_nonce + 1)
                        assert got == want, (round_no, got, want)
            finally:
                lspnet.set_client_write_drop_percent(0)
                lspnet.set_server_write_drop_percent(0)
    asyncio.run(scenario())
