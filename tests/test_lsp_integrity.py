"""Integrity gate: variable-length and corrupted messages.

Port of the reference lsp5_test.go scenarios: lengthened payloads are
truncated to Size and delivered; shortened payloads are silently rejected
(receiver gets nothing); bit-flipped payloads are rejected via checksum.
"""

import asyncio

from distributed_bitcoinminer_tpu import lspnet
from distributed_bitcoinminer_tpu.lsp import Params
from distributed_bitcoinminer_tpu.lsp.client import new_async_client
from distributed_bitcoinminer_tpu.lsp.server import new_async_server


def params_with(epoch_ms=50, limit=30):
    return Params(epoch_limit=limit, epoch_millis=epoch_ms,
                  window_size=5, max_backoff_interval=1)


async def _pair(params):
    server = await new_async_server(0, params)
    client = await new_async_client(f"127.0.0.1:{server.port}", params)
    return server, client


class TestVariableLength:
    def test_lengthened_messages_are_truncated(self):
        """Extended payloads must be cut back to Size and then pass the
        checksum (ref TestVariableLengthMsgServer)."""
        async def scenario():
            params = params_with()
            server, client = await _pair(params)
            lspnet.set_msg_lengthening_percent(100)
            n = 5
            for i in range(n):
                client.write(f"msg{i}".encode())
            got = []
            while len(got) < n:
                _, payload = await asyncio.wait_for(server.read(), 10)
                if isinstance(payload, bytes):
                    got.append(payload)
            assert got == [f"msg{i}".encode() for i in range(n)]
            lspnet.set_msg_lengthening_percent(0)
            await client.close()
            await server.close()
        asyncio.run(scenario())

    def test_shortened_messages_never_delivered(self):
        """Truncated-on-the-wire payloads must be silently dropped; with
        the fault always on, the receiver gets nothing
        (ref TestVariableLengthMsgClient + 'correct if nothing received')."""
        async def scenario():
            params = params_with(epoch_ms=50, limit=100)
            server, client = await _pair(params)
            lspnet.set_msg_shortening_percent(100)
            for i in range(3):
                client.write(f"blocked{i}".encode())
            try:
                await asyncio.wait_for(server.read(), 0.8)
                raise AssertionError("shortened message was delivered")
            except asyncio.TimeoutError:
                pass
            lspnet.set_msg_shortening_percent(0)
            # Close the CLIENT too: its engine tasks must not outlive the
            # scenario (the no_task_leaks fixture caught exactly this).
            await client.close()
            await server.close()
        asyncio.run(scenario())


class TestCorruption:
    def test_corrupted_messages_rejected_by_checksum(self):
        async def scenario():
            params = params_with(epoch_ms=50, limit=100)
            server, client = await _pair(params)
            lspnet.set_msg_corrupted(True)
            for i in range(3):
                client.write(f"tainted{i}".encode())
            try:
                await asyncio.wait_for(server.read(), 0.8)
                raise AssertionError("corrupted message was delivered")
            except asyncio.TimeoutError:
                pass
            lspnet.set_msg_corrupted(False)
            # Once corruption stops, retransmits deliver the originals.
            got = []
            while len(got) < 3:
                _, payload = await asyncio.wait_for(server.read(), 10)
                if isinstance(payload, bytes):
                    got.append(payload)
            assert got == [f"tainted{i}".encode() for i in range(3)]
            await client.close()
            await server.close()
        asyncio.run(scenario())

    def test_corruption_server_to_client(self):
        async def scenario():
            params = params_with(epoch_ms=50, limit=100)
            server, client = await _pair(params)
            client.write(b"reg")
            conn_id, _ = await asyncio.wait_for(server.read(), 5)
            lspnet.set_msg_corrupted(True)
            server.write(conn_id, b"poisoned")
            try:
                await asyncio.wait_for(client.read(), 0.8)
                raise AssertionError("corrupted message was delivered")
            except asyncio.TimeoutError:
                pass
            lspnet.set_msg_corrupted(False)
            got = await asyncio.wait_for(client.read(), 10)
            assert got == b"poisoned"
            await client.close()
            await server.close()
        asyncio.run(scenario())
