"""dbmlint analyzer tests (ISSUE 7).

Each analyzer gets a known-bad/known-good fixture pair proving it
catches its bug class and stays quiet on the sanctioned shape; the
repo-wide test pins the tree clean against the checked-in baseline
(which is how every analyzer finding fixed in this PR is locked in);
the mechanics tests cover suppression comments and the monotonic
baseline workflow.

Everything here is pure AST — no JAX, no network — so the module runs
in milliseconds.
"""

import json
import os
import subprocess
import sys
import textwrap

from distributed_bitcoinminer_tpu.analysis import (compare, load_baseline,
                                                   run_repo, run_source)
from distributed_bitcoinminer_tpu.analysis.core import (Finding,
                                                        baseline_path,
                                                        save_baseline)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def snip(src: str) -> str:
    return textwrap.dedent(src)


# ------------------------------------------------------------- loop-block

LOOP_BAD = snip("""
    import time
    import subprocess

    class Worker:
        async def serve(self):
            time.sleep(1.0)                  # blocks the loop

        async def probe(self):
            subprocess.run(["true"])         # blocks the loop

        async def resolve(self, msg):
            searcher = self._get_searcher(msg.data)   # backend init!
            return searcher
""")

LOOP_GOOD = snip("""
    import asyncio
    import time

    class Worker:
        async def serve(self):
            await asyncio.sleep(0.1)          # asyncio sleep: fine
            await asyncio.to_thread(self._scan)

        def _scan(self):
            time.sleep(1.0)                   # sync method: runs off-loop

        async def pipelined(self, msg):
            # Passing the method REFERENCE to a worker thread is the
            # sanctioned hop — no call happens on the loop.
            return await asyncio.to_thread(self._resolve_and_dispatch, msg)

        def _resolve_and_dispatch(self, msg):
            return self._get_searcher(msg.data)
""")


def test_loopblock_catches_known_bad():
    found = run_source("loop-block", LOOP_BAD)
    kinds = {f.key.rsplit(":", 1)[-1] for f in found}
    assert len(found) == 3
    assert "time.sleep" in kinds and "subprocess.run" in kinds
    assert any("_get_searcher" in k for k in kinds)


def test_loopblock_clean_on_known_good():
    assert run_source("loop-block", LOOP_GOOD) == []


def test_loopblock_scoped_to_apps_and_lsp():
    # The same bad code outside apps/ and lsp/ is out of scope.
    rel = "distributed_bitcoinminer_tpu/ops/_fixture.py"
    assert run_source("loop-block", LOOP_BAD, rel=rel) == []


# ------------------------------------------------------------ cardinality

CARD_BAD = snip("""
    class Sched:
        def observe(self, metrics, conn_id, rate):
            metrics.gauge("miner_rate_nps", miner=str(conn_id)).set(rate)
""")

CARD_GOOD_RETIRED = snip("""
    class Sched:
        def observe(self, metrics, conn_id, rate):
            metrics.gauge("miner_rate_nps", miner=str(conn_id)).set(rate)

        def on_drop(self, metrics, conn_id):
            metrics.remove("miner_rate_nps", miner=str(conn_id))
""")

CARD_GOOD_LITERAL = snip("""
    def setup(metrics):
        metrics.counter("drops", reason="checksum").inc()
        outcomes = {k: metrics.counter("outcomes", outcome=k)
                    for k in ("ok", "exhausted")}
        return outcomes
""")

# Trace-track extension (ISSUE 10): .track() with a dynamic entity
# label is the known-bad shape unless the module also retires it via
# .retire(...) — a .remove() does NOT vouch for a track (different
# registry, different lifecycle).
CARD_TRACK_BAD = snip("""
    class Sched:
        def span(self, tracks, conn_id):
            tracks.track("trace_track", miner=str(conn_id))
""")

CARD_TRACK_GOOD = snip("""
    class Sched:
        def span(self, tracks, conn_id):
            tracks.track("trace_track", miner=str(conn_id))

        def on_drop(self, tracks, conn_id):
            tracks.retire("trace_track", miner=str(conn_id))
""")

CARD_TRACK_WRONG_RETIREMENT = snip("""
    class Sched:
        def span(self, tracks, metrics, conn_id):
            tracks.track("trace_track", miner=str(conn_id))

        def on_drop(self, metrics, conn_id):
            metrics.remove("trace_track", miner=str(conn_id))
""")


def test_cardinality_catches_unretired_dynamic_label():
    found = run_source("cardinality", CARD_BAD)
    assert len(found) == 1
    assert "miner_rate_nps" in found[0].message
    assert "retirement" in found[0].message


def test_cardinality_accepts_retirement_path():
    assert run_source("cardinality", CARD_GOOD_RETIRED) == []


def test_cardinality_accepts_literals_and_bounded_comprehensions():
    assert run_source("cardinality", CARD_GOOD_LITERAL) == []


def test_cardinality_catches_unretired_trace_track():
    found = run_source("cardinality", CARD_TRACK_BAD)
    assert len(found) == 1
    assert "trace_track" in found[0].message
    assert ".retire(" in found[0].message


def test_cardinality_accepts_track_retirement_path():
    assert run_source("cardinality", CARD_TRACK_GOOD) == []


def test_cardinality_track_not_vouched_by_remove():
    """A ``.remove()`` on the same name is a METRIC retirement; it must
    not satisfy a ``.track()`` site (different registry, different
    lifecycle) — the known-bad cross-vouching shape."""
    found = run_source("cardinality", CARD_TRACK_WRONG_RETIREMENT)
    assert len(found) == 1 and ".retire(" in found[0].message


# ISSUE 18: the rollup plane's per-source (``proc``-labeled) series
# obey the same rule with their own pair — a dynamic ``proc`` label on
# a .proc_series() site needs a same-module .retire_proc() path; no
# other retirement method vouches for it.
CARD_PROC_BAD = snip("""
    class Console:
        def fold(self, sources, blob):
            sources.proc_series("rollup_sources", proc=blob.proc)
""")

CARD_PROC_GOOD = snip("""
    class Console:
        def fold(self, sources, blob):
            sources.proc_series("rollup_sources", proc=blob.proc)

        def on_fence(self, sources, blob):
            sources.retire_proc("rollup_sources", proc=blob.proc)
""")

CARD_PROC_WRONG_RETIREMENT = snip("""
    class Console:
        def fold(self, sources, metrics, blob):
            sources.proc_series("rollup_sources", proc=blob.proc)

        def on_fence(self, metrics, blob):
            metrics.remove("rollup_sources", proc=blob.proc)
""")


def test_cardinality_catches_unretired_proc_series():
    found = run_source("cardinality", CARD_PROC_BAD)
    assert len(found) == 1
    assert "rollup_sources" in found[0].message
    assert ".retire_proc(" in found[0].message


def test_cardinality_accepts_proc_series_retirement_path():
    assert run_source("cardinality", CARD_PROC_GOOD) == []


def test_cardinality_proc_series_not_vouched_by_remove():
    found = run_source("cardinality", CARD_PROC_WRONG_RETIREMENT)
    assert len(found) == 1 and ".retire_proc(" in found[0].message


# ----------------------------------------------------------- knob-hygiene

KNOB_BAD = snip("""
    import os

    def load():
        a = os.environ.get("DBM_FIXTURE_KNOB", "1")
        b = os.environ["DBM_FIXTURE_KNOB2"]
        c = "DBM_FIXTURE_KNOB3" in os.environ
        return a, b, c
""")

KNOB_GOOD = snip("""
    import os
    from ..utils._env import int_env, str_env

    def load():
        a = int_env("DBM_FIXTURE_KNOB", 1)
        b = str_env("DBM_FIXTURE_KNOB2", "")
        os.environ["DBM_FIXTURE_KNOB3"] = "1"   # a WRITE: not a read
        return a, b
""")

KNOB_COMPUTED = snip("""
    from ..utils._env import int_env

    def load(name):
        return int_env(name, 1)     # computed knob name: ungreppable
""")


def test_knobs_catch_direct_reads():
    found = run_source("knob-hygiene", KNOB_BAD)
    assert len(found) == 3
    assert all("route it through utils/_env.py" in f.message
               for f in found)


def test_knobs_accept_env_helpers_and_writes():
    assert run_source("knob-hygiene", KNOB_GOOD) == []


def test_knobs_flag_computed_knob_name():
    found = run_source("knob-hygiene", KNOB_COMPUTED)
    assert len(found) == 1 and "computed knob name" in found[0].message


def test_knobs_allow_the_env_module_itself():
    rel = "distributed_bitcoinminer_tpu/utils/_env.py"
    assert run_source("knob-hygiene", KNOB_BAD, rel=rel) == []


# ------------------------------------------------------------- jit-static

JIT_BAD = snip("""
    import functools
    import jax

    @functools.partial(jax.jit, static_argnames=("rem", "nbatches"))
    def search_fixture(x, *, rem, nbatches):
        return x

    def caller(x, span, batch):
        # computed INLINE at the boundary: unbounded signature set
        return search_fixture(x, rem=7, nbatches=span // batch + 1)

    def caller_via_local(x, span, batch):
        n = span // batch            # same hazard, one assignment away
        return search_fixture(x, rem=7, nbatches=n)
""")

JIT_GOOD = snip("""
    import functools
    import jax

    @functools.partial(jax.jit, static_argnames=("rem", "nbatches"))
    def search_fixture(x, *, rem, nbatches):
        return x

    NBATCHES = 8

    def caller(x, plan, nbatches):
        # literals, module constants, precomputed plan state, and
        # forwarded parameters are the quantized-upstream shapes.
        search_fixture(x, rem=7, nbatches=NBATCHES)
        search_fixture(x, rem=plan.rem, nbatches=plan.nbatches)
        for _, nb in plan.subs:
            search_fixture(x, rem=plan.rem, nbatches=nb)
        return search_fixture(x, rem=7, nbatches=nbatches)
""")

JIT_REL = "distributed_bitcoinminer_tpu/ops/_fixture.py"


def test_jitstatic_catches_boundary_computed_static_args():
    found = run_source("jit-static", JIT_BAD, rel=JIT_REL)
    assert len(found) == 2
    assert {f.key.split(":")[2] for f in found} == \
        {"caller", "caller_via_local"}
    assert all("nbatches" in f.message for f in found)


def test_jitstatic_clean_on_quantized_shapes():
    assert run_source("jit-static", JIT_GOOD, rel=JIT_REL) == []


def test_jitstatic_scoped_to_compute_dirs():
    rel = "distributed_bitcoinminer_tpu/apps/_fixture.py"
    assert run_source("jit-static", JIT_BAD, rel=rel) == []


JIT_QUANTIZED = snip("""
    import functools
    import jax

    from .search import pow2_bucket

    @functools.partial(jax.jit, static_argnames=("nrows",))
    def batch_fixture(x, *, nrows):
        return x

    def caller(x, rows):
        # A registered quantizer at the boundary: bounded by the
        # callee's contract (ISSUE 9 batch-geometry statics).
        ok = batch_fixture(x, nrows=pow2_bucket(len(rows)))
        # The same expression WITHOUT the quantizer stays a finding.
        return ok, batch_fixture(x, nrows=len(rows))
""")


def test_jitstatic_bounded_quantizer_call_is_stable():
    """The pow2_bucket quantizer (ISSUE 9): a call to a registered
    bounded quantizer at a static boundary is clean; the raw runtime
    value right next to it still fails — teaching the analyzer, not
    blanket-suppressing the site."""
    found = run_source("jit-static", JIT_QUANTIZED, rel=JIT_REL)
    assert len(found) == 1
    assert "nrows" in found[0].message


JIT_DEVLOOP = snip("""
    import functools
    import jax

    from .search import devloop_cap

    @functools.partial(jax.jit, static_argnames=("cap",))
    def devloop_fixture(x, nsub, *, cap):
        return x

    def caller(x, lo_i, hi_i, batch):
        nsub = (hi_i - lo_i + batch) // batch
        # The devloop static backstop (ISSUE 19): pow2-quantized by
        # devloop_cap's contract; the LIVE count nsub is traced.
        ok = devloop_fixture(x, nsub, cap=devloop_cap(nsub))
        # The raw runtime count at the static boundary still fails.
        return ok, devloop_fixture(x, nsub, cap=nsub)
""")


def test_jitstatic_devloop_cap_is_stable():
    """The devloop_cap quantizer (ISSUE 19): the in-kernel loop's
    static iteration backstop is bounded by delegation to pow2_bucket,
    so a devloop launch site passing ``cap=devloop_cap(nsub)`` is
    clean while the unquantized sub count next to it still fails."""
    found = run_source("jit-static", JIT_DEVLOOP, rel=JIT_REL)
    assert len(found) == 1
    assert "cap" in found[0].message


# ------------------------------------------------------------ thread-state

THREAD_BAD = snip("""
    import asyncio

    class Scheduler:
        def __init__(self):
            self.queue = []

        async def on_result(self):
            self.queue.append(1)             # event-loop side
            await asyncio.to_thread(self._work)

        def _work(self):
            self.queue.pop()                 # worker-thread side
""")

THREAD_GOOD_TABLE = THREAD_BAD.replace(
    "    def __init__(self):",
    "    THREAD_SHARED = {\n"
    "        \"queue\": \"serialized: one worker at a time\",\n"
    "    }\n\n"
    "    def __init__(self):")

THREAD_GOOD_LOCK = snip("""
    import asyncio
    import threading

    class Scheduler:
        def __init__(self):
            self.queue = []
            self._lock = threading.Lock()

        async def on_result(self):
            with self._lock:
                self.queue.append(1)
            await asyncio.to_thread(self._work)

        def _work(self):
            with self._lock:
                self.queue.pop()
""")


def test_threadstate_catches_undeclared_cross_thread_attr():
    found = run_source("thread-state", THREAD_BAD)
    assert len(found) == 1
    assert "Scheduler.queue" in found[0].message
    assert "THREAD_SHARED" in found[0].message


THREAD_BAD_LOOP_WRITE = snip("""
    import asyncio

    class Scheduler:
        def __init__(self):
            self.pool_rate = None

        async def on_result(self):
            self.pool_rate = 2.0             # event-loop WRITE
            await asyncio.to_thread(self._work)

        def _work(self):
            return self.pool_rate            # worker-thread READ
""")


def test_threadstate_catches_loop_written_thread_read():
    found = run_source("thread-state", THREAD_BAD_LOOP_WRITE)
    assert len(found) == 1
    assert "Scheduler.pool_rate" in found[0].message


def test_threadstate_accepts_ownership_table():
    assert run_source("thread-state", THREAD_GOOD_TABLE) == []


def test_threadstate_accepts_lock_guard():
    assert run_source("thread-state", THREAD_GOOD_LOCK) == []


# --------------------------------------------------------- lock-discipline

LOCK_BAD_AWAIT = snip("""
    import asyncio
    import threading

    class Actor:
        def __init__(self):
            self._lock = threading.Lock()

        async def serve(self):
            with self._lock:
                await asyncio.sleep(0.1)     # parked holding the lock
""")

LOCK_BAD_BLOCKING = snip("""
    import threading
    import time

    class Actor:
        def __init__(self):
            self._mu = threading.Lock()      # name has no 'lock' hint

        def probe(self):
            with self._mu:
                probe_backend(3.0)           # minutes under a lock

        async def drain(self):
            async with self.state_lock:
                time.sleep(0.5)              # blocking under asyncio lock
""")

LOCK_GOOD = snip("""
    import asyncio

    class Actor:
        async def serve(self):
            async with self._lock:
                self.count += 1              # pure state flip: fine

        def bump(self):
            with self._lock:
                self.count += 1

        async def read(self):
            with open("f") as fh:            # not a lock
                await asyncio.sleep(0)
""")


def test_lockdiscipline_catches_sync_lock_across_await():
    found = run_source("lock-discipline", LOCK_BAD_AWAIT)
    assert len(found) == 1
    assert "held across an await" in found[0].message
    assert "self._lock" in found[0].key


def test_lockdiscipline_catches_blocking_call_under_lock():
    found = run_source("lock-discipline", LOCK_BAD_BLOCKING)
    keys = {f.key for f in found}
    # The ctor-assignment tracking catches `_mu` (no name hint), and the
    # name hint catches `state_lock` with no assignment in sight.
    assert any("self._mu:probe_backend" in k for k in keys)
    assert any("self.state_lock:time.sleep" in k for k in keys)
    assert len(found) == 2


def test_lockdiscipline_clean_on_known_good():
    assert run_source("lock-discipline", LOCK_GOOD) == []


def test_lockdiscipline_scoped_to_apps_and_lsp():
    rel = "distributed_bitcoinminer_tpu/utils/_fixture.py"
    assert run_source("lock-discipline", LOCK_BAD_AWAIT, rel=rel) == []


def test_lockdiscipline_suppression_needs_matching_analyzer():
    src = LOCK_BAD_AWAIT.replace(
        "await asyncio.sleep(0.1)     # parked holding the lock",
        "await asyncio.sleep(0.1)  "
        "# dbmlint: ok[lock-discipline] bounded: test rig")
    assert run_source("lock-discipline", src) == []


# ---------------------------------------------------- suppression comments

def test_ok_comment_suppresses_matching_analyzer():
    src = LOOP_BAD.replace(
        "time.sleep(1.0)                  # blocks the loop",
        "time.sleep(1.0)  # dbmlint: ok[loop-block] test rig")
    found = run_source("loop-block", src)
    assert len(found) == 2      # the other two still fire


def test_ok_comment_for_other_analyzer_does_not_suppress():
    src = LOOP_BAD.replace(
        "time.sleep(1.0)                  # blocks the loop",
        "time.sleep(1.0)  # dbmlint: ok[cardinality] nope")
    assert len(run_source("loop-block", src)) == 3


# ------------------------------------------------------- baseline mechanics

def _finding(key):
    return Finding("loop-block", "f.py", 1, key, "msg " + key)


def test_compare_splits_new_known_stale():
    findings = [_finding("a"), _finding("b")]
    new, known, stale = compare(findings, {"b": "msg b", "c": "msg c"})
    assert [f.key for f in new] == ["a"]
    assert [f.key for f in known] == ["b"]
    assert stale == ["c"]


def test_baseline_roundtrip(tmp_path):
    path = str(tmp_path / "baseline.json")
    save_baseline(path, [_finding("k1"), _finding("k0")])
    loaded = load_baseline(path)
    assert list(loaded) == ["k0", "k1"]     # sorted, stable for diffs
    with open(path) as f:
        assert "shrink" in json.load(f)["comment"]


def test_update_refuses_to_grow_without_force(tmp_path):
    # CLI-level: a repo-shaped temp tree with one bad file and an empty
    # baseline; --update-baseline must refuse, --force must accept.
    pkg = tmp_path / "distributed_bitcoinminer_tpu"
    (pkg / "apps").mkdir(parents=True)
    (pkg / "analysis").mkdir()
    (pkg / "apps" / "bad.py").write_text(
        "import time\nclass W:\n    async def f(self):\n"
        "        time.sleep(1)\n")
    env = {**os.environ, "PYTHONPATH": REPO}
    base = [sys.executable, os.path.join(REPO, "scripts", "dbmlint.py"),
            "--repo", str(tmp_path)]
    r = subprocess.run(base + ["--update-baseline"], env=env,
                       capture_output=True, text=True)
    assert r.returncode == 1 and "refusing to GROW" in r.stderr
    r = subprocess.run(base + ["--update-baseline", "--force"], env=env,
                       capture_output=True, text=True)
    assert r.returncode == 0
    r = subprocess.run(base, env=env, capture_output=True, text=True)
    assert r.returncode == 0    # baselined: clean now
    # A partial (--analyzer) run must neither rewrite the baseline (it
    # would flush other analyzers' entries) nor report their entries as
    # stale (code-review findings on the first cut).
    r = subprocess.run(base + ["--analyzer", "cardinality",
                               "--update-baseline"], env=env,
                       capture_output=True, text=True)
    assert r.returncode == 2 and "requires a full run" in r.stderr
    r = subprocess.run(base + ["--analyzer", "cardinality"], env=env,
                       capture_output=True, text=True)
    assert r.returncode == 0 and "stale" not in r.stdout


# ------------------------------------------------------------- repo-wide

def test_repo_is_clean_against_checked_in_baseline():
    """THE gate (acceptance): the tree has no new findings."""
    findings = run_repo(REPO)
    baseline = load_baseline(baseline_path(REPO))
    new, _known, _stale = compare(findings, baseline)
    assert new == [], "\n".join(f.render() for f in new)


def test_cli_exits_zero_on_repo_without_importing_jax():
    code = (
        "import sys; sys.path.insert(0, %r); "
        "from distributed_bitcoinminer_tpu.analysis import run_repo, "
        "load_baseline, compare; "
        "from distributed_bitcoinminer_tpu.analysis.core import "
        "baseline_path; "
        "fs = run_repo(%r); "
        "new, _, _ = compare(fs, load_baseline(baseline_path(%r))); "
        "assert not new, new; "
        "assert 'jax' not in sys.modules, 'lint must not import JAX'; "
        "print('ok')" % (REPO, REPO, REPO))
    r = subprocess.run([sys.executable, "-c", code], capture_output=True,
                       text=True, cwd=REPO)
    assert r.returncode == 0, r.stderr
    assert r.stdout.strip() == "ok"


def test_cli_gate_subprocess():
    r = subprocess.run(
        [sys.executable, os.path.join(REPO, "scripts", "dbmlint.py")],
        capture_output=True, text=True, cwd=REPO)
    assert r.returncode == 0, r.stdout + r.stderr
    assert "clean" in r.stdout
