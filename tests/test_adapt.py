"""Self-tuning control plane units (ISSUE 13, ``apps/adapt.py``).

Covers the controller family the scheduler mounts under ``DBM_ADAPT``:
AIMD mechanics (convergence to setpoint on a scripted latency series,
hysteresis dead-band, hard floor/ceiling clamps, the bounded
proportional probe), the oscillation-amplitude audit the dbmcheck
``adaptive_control`` scenario runs, each controller's signal semantics
(lease-margin guard, mouse-flood widen / pipeline-bubble collapse,
queue-age-slope admission with the service-rate anchor), the live
token-bucket re-rate, plane plumbing (tick rate-limit, span
whitelisting, congestion queue bound) — and the ``DBM_ADAPT=0`` parity
pin the tier-1 knob-off matrix leg re-runs: byte-identical replies,
zero controller state.
"""

from __future__ import annotations

import pytest

from distributed_bitcoinminer_tpu.apps.adapt import (
    AdaptPlane, AdmissionController, AimdValue, ChunkSizeController,
    CoalesceWindowController, oscillation_ratio, oscillation_ratios)
from distributed_bitcoinminer_tpu.apps.qos import TokenBucket
from distributed_bitcoinminer_tpu.apps.scheduler import Scheduler
from distributed_bitcoinminer_tpu.bitcoin.message import (Message,
                                                          MsgType,
                                                          new_request,
                                                          new_result)
from distributed_bitcoinminer_tpu.utils.config import (AdaptParams,
                                                       LeaseParams,
                                                       QosParams,
                                                       adapt_from_env)
from distributed_bitcoinminer_tpu.utils.metrics import Registry

MINER_A, MINER_B = 1, 2
TEN_X, TEN_Y = 10, 11


class FakeClock:
    def __init__(self, t=100.0):
        self.t = t

    def __call__(self):
        return self.t

    def advance(self, dt):
        self.t += dt


# ------------------------------------------------------------- AimdValue

def test_aimd_hard_clamps_hold_under_any_sequence():
    clk = FakeClock()
    v = AimdValue(1.0, floor=0.5, ceil=2.0, add=0.3, clock=clk)
    for _ in range(50):
        v.increase()
    assert v.value == 2.0
    for _ in range(50):
        v.decrease()
    assert v.value == 0.5
    for _t, x in v.history:
        assert 0.5 <= x <= 2.0


def test_aimd_history_records_only_movement():
    clk = FakeClock()
    v = AimdValue(2.0, floor=0.5, ceil=2.0, add=0.3, clock=clk)
    n0 = len(v.history)
    assert not v.increase()          # already at the ceiling: no-op
    assert len(v.history) == n0 and v.adjustments == 0
    assert v.decrease()
    assert len(v.history) == n0 + 1 and v.adjustments == 1


def test_aimd_proportional_probe_bounds_growth_ratio():
    """``add_frac`` recovers large values quickly but each step's
    growth ratio stays <= 1 + add_frac (the oscillation-bound term)."""
    clk = FakeClock()
    v = AimdValue(1000.0, floor=1.0, ceil=1e5, add=8.0, add_frac=0.1,
                  clock=clk)
    before = v.value
    v.increase()
    assert v.value == pytest.approx(before * 1.1)
    small = AimdValue(10.0, floor=1.0, ceil=1e5, add=8.0, add_frac=0.1,
                      clock=clk)
    small.increase()                 # constant term dominates when small
    assert small.value == pytest.approx(18.0)


def test_aimd_decrease_floored_holds_at_anchor():
    clk = FakeClock()
    v = AimdValue(100.0, floor=1.0, ceil=1e5, add=8.0, mul=0.5,
                  clock=clk)
    assert v.decrease_floored(80.0)
    assert v.value == 80.0           # cut to the anchor, not through it
    assert not v.decrease_floored(80.0)   # at the anchor: HOLD
    assert v.value == 80.0
    assert v.decrease_floored(None)  # no anchor: plain multiplicative
    assert v.value == 40.0


# ------------------------------------------------------ oscillation_ratio

def test_oscillation_ratio_short_and_monotone_series():
    assert oscillation_ratio([]) == 1.0
    assert oscillation_ratio([(0, 5.0), (1, 4.0)]) == 1.0
    # A pure monotone descent (the open-admission transient) has no
    # post-transient cycle at all.
    hist = [(t, 100.0 * 0.7 ** t) for t in range(8)]
    assert oscillation_ratio(hist) == 1.0


def test_oscillation_ratio_skips_transient_measures_sawtooth():
    values = [100.0, 50.0, 25.0, 20.0, 25.0, 20.0, 25.0]
    hist = [(t, v) for t, v in enumerate(values)]
    # The 100 -> 20 descent is the transient; the steady sawtooth's
    # amplitude is 25/20.
    assert oscillation_ratio(hist) == pytest.approx(1.25)


def test_oscillation_ratio_flags_growing_cycle():
    values = [1.0, 2.0, 1.0, 4.0, 1.0, 8.0]
    hist = [(t, v) for t, v in enumerate(values)]
    assert oscillation_ratio(hist) == pytest.approx(8.0)


def test_oscillation_ratios_episode_vs_limit_cycle():
    """The stability audit's discriminator: ONE wide swing is a
    congestion episode (descent + recovery ramp), TWO is a limit
    cycle. A single dip-and-recover history shows exactly one ratio
    over a 5x bound; a repeated wide sawtooth shows several."""
    episode = [10.0, 50.0, 20.0, 14.0, 22.0, 30.0, 46.0, 120.0]
    ratios = oscillation_ratios([(t, v) for t, v in enumerate(episode)])
    assert sum(1 for r in ratios if r > 5.0) == 1
    cycle = [1.0, 8.0, 1.0, 8.0, 1.0, 8.0]
    ratios = oscillation_ratios([(t, v) for t, v in enumerate(cycle)])
    assert sum(1 for r in ratios if r > 5.0) >= 2


# --------------------------------------------------- ChunkSizeController

def _converge_chunk(ctl, slow, steps=40, samples=6):
    """Run the controller against a proportional plant: executing a
    chunk planned at ``value`` seconds takes ``value * slow`` wall
    seconds (a pool ``slow``x slower than the rate EWMA believes)."""
    for _ in range(steps):
        for _ in range(samples):
            ctl.observe(ctl.aimd.value * slow, 1.0)
        ctl.tick()
    return ctl.aimd.value * slow     # the latency the plant now shows


def test_chunk_controller_converges_to_setpoint_scripted_series():
    clk = FakeClock()
    ctl = ChunkSizeController(1.0, setpoint_s=1.0, band=0.2, clock=clk)
    # Pool 3x slower than planned (ideal value 1/3): AIMD settles into
    # a bounded sawtooth AROUND the setpoint — the final value sits
    # within one multiplicative cycle of ideal and the post-transient
    # oscillation amplitude is bounded by one 0.5x step.
    lat = _converge_chunk(ctl, slow=3.0)
    assert 0.7 <= lat <= 1.6
    assert 0.2 <= ctl.aimd.value <= 0.55
    assert oscillation_ratio(list(ctl.aimd.history)) <= 2.5
    # Pool 4x faster than planned: value grows until latency re-enters
    # the band (additive, so it approaches from below).
    ctl2 = ChunkSizeController(1.0, setpoint_s=1.0, band=0.2, clock=clk)
    lat2 = _converge_chunk(ctl2, slow=0.25, steps=80)
    assert lat2 == pytest.approx(1.0, rel=0.35)
    assert ctl2.aimd.value > 2.5


def test_chunk_controller_dead_band_no_churn():
    clk = FakeClock()
    ctl = ChunkSizeController(1.0, setpoint_s=1.0, band=0.35, clock=clk)
    for lat in (0.9, 1.1, 0.75, 1.3, 1.0):
        for _ in range(4):
            ctl.observe(lat, 1.0)
        assert ctl.tick() is None    # inside the band: nothing moves
    assert ctl.aimd.adjustments == 0


def test_chunk_controller_lease_margin_guard_overrides_latency():
    clk = FakeClock()
    ctl = ChunkSizeController(1.0, setpoint_s=1.0, band=0.35, clock=clk)
    # Latency inside the band, but a chunk finished with only 10% of
    # its lease left: one stall from a blow — shrink regardless.
    ctl.observe(1.0, 0.1)
    assert ctl.tick() == pytest.approx(0.5)


def test_chunk_controller_settle_tick_discards_stale_samples():
    """After an adjustment the next tick is a SETTLE tick: samples
    still arriving from old-size chunks are drained and the EWMA
    reset, so measurement lag cannot turn one decrease into a
    multiplicative cascade (the dbmcheck-caught amplitude violation)."""
    clk = FakeClock()
    ctl = ChunkSizeController(1.0, setpoint_s=1.0, band=0.2, clock=clk)
    ctl.observe(5.0, 1.0)
    assert ctl.tick() == pytest.approx(0.5)     # honest decrease
    ctl.observe(5.0, 1.0)                       # STALE old-size sample
    assert ctl.tick() is None                   # settle: no cascade
    ctl.observe(5.0, 1.0)                       # still slow, fresh EWMA
    assert ctl.tick() == pytest.approx(0.25)    # now it may act again


def test_chunk_controller_no_samples_no_tick():
    clk = FakeClock()
    ctl = ChunkSizeController(1.0, setpoint_s=1.0, band=0.2, clock=clk)
    assert ctl.tick() is None
    assert ctl.aimd.adjustments == 0


def test_chunk_controller_clamps_under_divergent_plant():
    clk = FakeClock()
    ctl = ChunkSizeController(1.0, setpoint_s=1.0, band=0.1, clock=clk)
    # A plant whose latency is huge regardless of the value (a wedged
    # pool): the value parks at the FLOOR, never below.
    for _ in range(60):
        ctl.observe(50.0, 1.0)
        ctl.tick()
    assert ctl.aimd.value == ChunkSizeController.FLOOR_S
    ctl2 = ChunkSizeController(1.0, setpoint_s=1.0, band=0.1, clock=clk)
    for _ in range(200):
        ctl2.observe(1e-4, 1.0)      # instant pool: parks at the CEIL
        ctl2.tick()
    assert ctl2.aimd.value == ChunkSizeController.CEIL_S


# ----------------------------------------------- CoalesceWindowController

def test_window_controller_mouse_flood_widens():
    clk = FakeClock()
    ctl = CoalesceWindowController(0.25, band=0.35, clock=clk)
    clk.advance(1.0)
    for _ in range(10):              # 10 small arrivals/s x 0.25s >= 2
        ctl.observe_arrival(True)
    ctl.observe_wait(0.2)            # and the queue wait is non-trivial
    assert ctl.tick() == pytest.approx(0.30)


def test_window_controller_no_widen_when_unloaded():
    clk = FakeClock()
    ctl = CoalesceWindowController(0.25, band=0.35, clock=clk)
    clk.advance(1.0)
    for _ in range(10):
        ctl.observe_arrival(True)
    ctl.observe_wait(0.001)          # mice flow but nothing queues
    assert ctl.tick() is None
    clk.advance(1.0)
    ctl.observe_arrival(True)        # trickle, loaded: still no widen
    ctl.observe_wait(0.5)
    assert ctl.tick() is None


def test_window_controller_gap_bubbles_collapse_and_win():
    clk = FakeClock()
    ctl = CoalesceWindowController(0.4, band=0.35, clock=clk)
    clk.advance(1.0)
    for _ in range(20):              # flood signal present...
        ctl.observe_arrival(True)
    ctl.observe_wait(0.5)
    ctl.observe_gap(0.5)             # ...but the executor shows bubbles
    assert ctl.tick() == pytest.approx(0.2)   # collapse wins


def test_window_controller_lull_is_not_a_bubble():
    """Code review (ISSUE 13): gap_s is unbounded idle time — the
    first chunk after a 60s lull carries the whole lull, which must
    not seed the bubble EWMA; and with ZERO fresh gap samples a stale
    EWMA must not keep collapsing the window tick after tick."""
    clk = FakeClock()
    ctl = CoalesceWindowController(0.4, band=0.35, clock=clk)
    clk.advance(1.0)
    ctl.observe_gap(60.0)            # a lull, filtered at observe
    assert ctl._gap.value is None
    assert ctl.tick() is None
    # One honest bubble sample collapses ONCE; with no further fresh
    # samples the next ticks do nothing (no stale-EWMA walk to floor).
    clk.advance(1.0)
    ctl.observe_gap(0.5)
    assert ctl.tick() == pytest.approx(0.2)
    for _ in range(5):
        clk.advance(1.0)
        assert ctl.tick() is None
    assert ctl.aimd.value == pytest.approx(0.2)


# ------------------------------------------------- AdmissionController

def test_admission_starts_open_and_descends_on_rising_age():
    clk = FakeClock()
    ctl = AdmissionController(0.0, clock=clk)
    assert ctl.aimd.value == AdmissionController.RATE_CEIL
    assert ctl.tick(0.5) is None     # first sample only seeds the slope
    got = ctl.tick(0.8)              # rising, past MIN_AGE_S: decrease
    assert got == pytest.approx(AdmissionController.RATE_CEIL * 0.7)


def test_admission_additive_increase_on_falling_or_young_age():
    clk = FakeClock()
    ctl = AdmissionController(50.0, clock=clk)
    ctl.tick(0.8)
    up = ctl.tick(0.6)               # falling age
    assert up == pytest.approx(50.0 + 8.0)
    ctl2 = AdmissionController(50.0, clock=clk)
    ctl2.tick(0.05)
    up2 = ctl2.tick(0.1)             # rising but UNDER the age floor:
    assert up2 == pytest.approx(58.0)   # underloaded, keep probing


def test_admission_service_rate_anchors_the_decrease():
    clk = FakeClock()
    ctl = AdmissionController(100.0, clock=clk)
    ctl.observe_service_rate(90.0)   # the pool demonstrably serves 90/s
    ctl.tick(1.0)
    assert ctl.tick(2.0) == pytest.approx(70.0)    # 0.7x, above anchor
    assert ctl.tick(2.5) is None     # settle tick after the adjustment
    assert ctl.tick(3.0) == pytest.approx(63.0)    # cut TO the anchor
    ctl.tick(3.5)                                  # settle
    assert ctl.tick(4.0) is None                   # at the anchor: hold
    assert ctl.aimd.value == pytest.approx(63.0)


def test_admission_settle_tick_damps_cascade():
    """One adjustment per two ticks: the queue age needs a tick to
    respond to the new rate before the slope means anything — a
    monotone rising-age run may halve the rate at most every other
    tick (cascade depth bounded by the lag rule)."""
    clk = FakeClock()
    ctl = AdmissionController(1000.0, clock=clk)
    ages = [1.0, 1.2, 1.4, 1.6, 1.8, 2.0]
    changes = [ctl.tick(a) for a in ages]
    assert changes[1] is not None and changes[2] is None
    assert changes[3] is not None and changes[4] is None
    assert ctl.aimd.value == pytest.approx(1000.0 * 0.7 ** 3)


def test_admission_queue_bound_is_capacity_times_age_knee():
    clk = FakeClock()
    ctl = AdmissionController(0.0, clock=clk)
    assert ctl.queue_bound() is None          # no service rate observed
    ctl.observe_service_rate(100.0)
    assert ctl.queue_bound() == 30            # 100/s x 0.3s knee
    ctl2 = AdmissionController(0.0, clock=clk)
    ctl2.observe_service_rate(3.0)
    assert ctl2.queue_bound() == AdmissionController.QUEUE_MIN


def test_admission_shed_counted_when_bucket_empty():
    clk = FakeClock()
    ctl = AdmissionController(4.0, clock=clk)
    granted = sum(1 for _ in range(100) if ctl.admit())
    assert granted < 100 and ctl.shed == 100 - granted


def test_token_bucket_set_rate_settles_at_old_rate_first():
    clk = FakeClock()
    bucket = TokenBucket(10.0, 10.0, clk)
    for _ in range(10):
        assert bucket.take(1.0)
    assert not bucket.take(1.0)      # drained
    clk.advance(1.0)                 # 1s accrues 10 at the OLD rate
    bucket.set_rate(1000.0, burst=1000.0)
    got = sum(1 for _ in range(1000) if bucket.take(1.0))
    assert got == 10                 # re-rating minted nothing


# ------------------------------------------------------------ AdaptPlane

def _plane(clk, **kw):
    params = kw.pop("params", AdaptParams(enabled=True, tick_s=1.0))
    return AdaptPlane(params, Registry(), clk, **kw)


def test_plane_tick_rate_limited_and_applies_changes():
    clk = FakeClock()
    plane = _plane(clk)
    plane.chunk.observe(50.0, 1.0)   # way above setpoint: wants shrink
    assert plane.tick(0.0) == {}     # tick_s not elapsed: rate-limited
    clk.advance(1.1)
    out = plane.tick(0.0)
    assert out.get("chunk_s") == pytest.approx(0.5)
    assert plane.state()["chunk_adjustments"] == 1


def test_plane_span_whitelisting_rejects_non_numerics():
    clk = FakeClock()
    plane = _plane(clk)
    plane.observe_chunk(None, None,
                        span={"force_s": True, "gap_s": "bad"})
    assert plane.chunk._samples == 0          # bool is not a latency
    assert plane.window._gap.value is None
    plane.observe_chunk(None, None, span={"force_s": 0.4, "gap_s": 0.1})
    assert plane.chunk._samples == 1
    assert plane.window._gap.value == pytest.approx(0.1)


def test_plane_unsized_chunks_do_not_feed_the_sizing_loop():
    """A mouse's wholesale split is small because the REQUEST is small —
    its latency must not walk the chunk knob (module docstring)."""
    clk = FakeClock()
    plane = _plane(clk)
    plane.observe_chunk(0.001, 1.0, sized=False)
    assert plane.chunk._samples == 0
    plane.observe_chunk(0.001, 1.0, sized=True)
    assert plane.chunk._samples == 1


def test_plane_effective_max_queued_semantics():
    clk = FakeClock()
    plane = _plane(clk)
    assert plane.effective_max_queued(256) == 256   # no srv rate yet
    plane.admission.observe_service_rate(100.0)
    assert plane.effective_max_queued(256) == 30    # congestion knee
    assert plane.effective_max_queued(16) == 16     # static is tighter
    assert plane.effective_max_queued(0) == 30      # 0 = unbounded stock


def test_plane_statically_disabled_knob_stays_disabled():
    """chunk_s/small_s <= 0 is the repo 0-disables convention: the
    controllers tune live knobs, they never re-enable one an operator
    turned off."""
    clk = FakeClock()
    plane = _plane(clk, chunk_s=0.0, small_s=0.0)
    assert plane.chunk is None and plane.window is None
    assert plane.admission is not None


def test_plane_histories_expose_clamps_for_the_audit():
    clk = FakeClock()
    plane = _plane(clk)
    hist = plane.histories()
    assert set(hist) == {"chunk", "window", "admit"}
    floor, ceil, points = hist["chunk"]
    assert (floor, ceil) == (ChunkSizeController.FLOOR_S,
                             ChunkSizeController.CEIL_S)
    assert len(points) == 1           # the seeded starting value


# ------------------------------------------------- DBM_ADAPT=0 parity

class FakeServer:
    def __init__(self):
        self.writes = []
        self.closed = []

    def write(self, conn_id, payload):
        self.writes.append((conn_id, Message.from_json(payload)))

    def close_conn(self, conn_id):
        self.closed.append(conn_id)


def _drive(sched):
    sched._on_join(MINER_A)
    sched._on_join(MINER_B)
    sched._pool_rate = 100.0
    sched._on_request(TEN_X, new_request("alpha", 0, 999))
    sched._on_request(TEN_Y, new_request("beta", 0, 499))
    sched._on_request(TEN_X, new_request("gamma", 0, 99))
    for _ in range(400):
        popped = None
        for m in sched.miners:
            if m.pending:
                popped = m.pending[0]
                sched._on_result(m.conn_id,
                                 new_result(1_000_000 + popped.lower,
                                            popped.lower))
                break
        if popped is None:
            break


def test_adapt_off_is_bit_for_bit_stock(monkeypatch):
    """The tier-1 matrix-leg pin: DBM_ADAPT=0 builds NO plane, no
    adapt metric series exist, and every write the scheduler emits is
    identical to one built with the explicit disabled block. (Unset =
    DEFAULT ON since ISSUE 14 — the ISSUE 13 soak ran clean — so the
    off contract is now pinned through the explicit 0.)"""
    monkeypatch.delenv("DBM_ADAPT", raising=False)
    assert adapt_from_env().enabled          # the ISSUE 14 default flip
    monkeypatch.setenv("DBM_ADAPT", "0")
    assert not adapt_from_env().enabled
    env_sched = Scheduler(FakeServer(), lease=LeaseParams(),
                          qos=QosParams())           # adapt from env
    off_sched = Scheduler(FakeServer(), lease=LeaseParams(),
                          qos=QosParams(),
                          adapt=AdaptParams(enabled=False))
    assert env_sched.adapt_plane is None
    assert off_sched.adapt_plane is None
    _drive(env_sched)
    _drive(off_sched)
    assert [(c, m.to_json()) for c, m in env_sched.server.writes] == \
        [(c, m.to_json()) for c, m in off_sched.server.writes]
    snap = env_sched.metrics.snapshot()
    for family in snap.values():
        if isinstance(family, dict):
            assert not any(k.startswith("adapt") for k in family), family


def test_adapt_never_re_enables_disabled_planes():
    """Code review (ISSUE 13): controllers mount only over LIVE knobs.
    With QoS off there is no chunked path, no window grant, and no
    admission gate — DBM_ADAPT=1 must not tune those dead knobs (or
    report gauges for them); with only coalescing off, the window
    controller alone stays unmounted."""
    qos_off = Scheduler(FakeServer(), lease=LeaseParams(),
                        qos=QosParams(enabled=False),
                        adapt=AdaptParams(enabled=True))
    plane = qos_off.adapt_plane
    assert plane is not None
    assert plane.chunk is None and plane.window is None \
        and plane.admission is None
    # Unmounted controllers register NO series: a permanent
    # adapt_admit_rate=0.0 for a controller that does not exist reads
    # as "admission fully closed" to an operator.
    snap = qos_off.metrics.snapshot()
    for family in snap.values():
        if isinstance(family, dict):
            assert not any(k.startswith("adapt") for k in family), \
                family
    from distributed_bitcoinminer_tpu.utils.config import CoalesceParams
    co_off = Scheduler(FakeServer(), lease=LeaseParams(),
                       qos=QosParams(),
                       coalesce=CoalesceParams(enabled=False),
                       adapt=AdaptParams(enabled=True))
    plane = co_off.adapt_plane
    assert plane.window is None
    assert plane.chunk is not None and plane.admission is not None


def test_adapt_on_quiescent_controllers_replies_identical():
    """Default-on safety shape: with the plane MOUNTED but no tick
    elapsed (tick_s huge) and the admission bucket open, the scripted
    drive's writes are byte-identical to the off run — the observe
    hooks are pure measurement."""
    on = Scheduler(FakeServer(), lease=LeaseParams(), qos=QosParams(),
                   adapt=AdaptParams(enabled=True, tick_s=1e9))
    off = Scheduler(FakeServer(), lease=LeaseParams(), qos=QosParams(),
                    adapt=AdaptParams(enabled=False))
    assert on.adapt_plane is not None
    _drive(on)
    _drive(off)
    assert [(c, m.to_json()) for c, m in on.server.writes] == \
        [(c, m.to_json()) for c, m in off.server.writes]
    state = on.adapt_plane.state()
    assert state["chunk_adjustments"] == 0
    assert state["admit_shed"] == 0


# ------------------------------------------- per-miner setpoints (ISSUE 14)

def _per_miner_ctl(clk):
    return ChunkSizeController(1.0, setpoint_s=1.0, band=0.25, clock=clk,
                               per_miner=True)


def test_per_miner_forks_only_after_divergence():
    """The DBM_ADAPT_PER_MINER gate: per-miner values exist only once
    the pool's rate EWMAs diverge past the 4x ratio — a homogeneous
    pool keeps the single pool-wide knob (forking it adds noise)."""
    clk = FakeClock()
    ctl = _per_miner_ctl(clk)
    for _ in range(4):
        ctl.observe(None, 0.9, force_s=0.05, miner=MINER_A)
        ctl.observe(None, 0.9, force_s=3.0, miner=MINER_B)
    ctl.note_rate_ratio(2.0)                 # below the 4x gate
    assert ctl.tick_miners() == {}
    ctl.note_rate_ratio(None)                # < 2 measured miners
    assert ctl.tick_miners() == {}
    for _ in range(4):
        ctl.observe(None, 0.9, force_s=0.05, miner=MINER_A)
        ctl.observe(None, 0.9, force_s=3.0, miner=MINER_B)
    ctl.note_rate_ratio(100.0)               # heterogeneous pool
    per = ctl.tick_miners()
    assert set(per) == {MINER_A, MINER_B}


def test_per_miner_values_move_independently():
    """In a skewed pool the fast miner's chunk seconds walk UP (its
    chunks force far under the setpoint) while the slow miner's walk
    DOWN — the exact split one pool-wide value cannot express."""
    clk = FakeClock()
    ctl = _per_miner_ctl(clk)
    ctl.note_rate_ratio(100.0)
    values = {MINER_A: [], MINER_B: []}
    for _ in range(12):
        for _ in range(3):
            ctl.observe(None, 0.9, force_s=0.05, miner=MINER_A)
            ctl.observe(None, 0.9, force_s=3.0, miner=MINER_B)
        clk.advance(1.0)
        for conn, v in ctl.tick_miners().items():
            values[conn].append(v)
    assert values[MINER_A] and values[MINER_A][-1] > 1.0
    assert values[MINER_B] and values[MINER_B][-1] < 1.0
    # Hard clamps hold per miner too.
    assert all(ctl.FLOOR_S <= v <= ctl.CEIL_S
               for vs in values.values() for v in vs)


def test_per_miner_settle_tick_and_forget():
    """Each per-miner loop takes the same settle tick as the pool-wide
    one (stale old-size samples must not cascade), and a dropped miner's
    state retires."""
    clk = FakeClock()
    ctl = _per_miner_ctl(clk)
    ctl.note_rate_ratio(10.0)
    for _ in range(3):
        ctl.observe(None, 0.9, force_s=3.0, miner=MINER_A)
    assert MINER_A in ctl.tick_miners()      # decrease fires
    for _ in range(3):
        ctl.observe(None, 0.9, force_s=3.0, miner=MINER_A)
    assert ctl.tick_miners() == {}           # settle tick: no move
    ctl.forget_miner(MINER_A)
    assert MINER_A not in ctl._miners


def test_per_miner_off_keeps_no_state():
    """Default-off parity: per_miner=False accumulates nothing and
    tick_miners is always empty, whatever is observed."""
    clk = FakeClock()
    ctl = ChunkSizeController(1.0, setpoint_s=1.0, band=0.25, clock=clk)
    ctl.observe(None, 0.9, force_s=3.0, miner=MINER_A)
    ctl.note_rate_ratio(1000.0)
    assert ctl.tick_miners() == {}
    assert ctl._miners == {}


def test_per_miner_plane_applies_stripe_overrides():
    """End-to-end through the scheduler: with DBM_ADAPT_PER_MINER the
    per-miner values land on MinerPlane.chunk_s_overrides (the stripe
    planner's per-miner knob) and retire when the miner drops."""
    import time as _time
    sched = Scheduler(FakeServer(), lease=LeaseParams(),
                      qos=QosParams(),
                      adapt=AdaptParams(enabled=True, tick_s=0.0,
                                        per_miner=True))
    plane = sched.adapt_plane
    assert plane is not None and plane.chunk.per_miner
    sched._on_join(MINER_A)
    sched._on_join(MINER_B)
    ma = sched._find_miner(MINER_A)
    mb = sched._find_miner(MINER_B)
    ma.rate_ewma, mb.rate_ewma = 100_000.0, 1_000.0   # 100x skew
    for _ in range(3):
        plane.observe_chunk(None, 0.9, span={"force_s": 0.05},
                            sized=True, miner=MINER_A)
        plane.observe_chunk(None, 0.9, span={"force_s": 3.0},
                            sized=True, miner=MINER_B)
    _time.sleep(0.01)
    sched._apply_adapt()
    assert MINER_A in sched.miner_plane.chunk_s_overrides
    assert MINER_B in sched.miner_plane.chunk_s_overrides
    # The forks seed from the (possibly just-adjusted) pool-wide value,
    # so pin the SPLIT, not absolutes: the fast miner's seconds walk up
    # relative to the slow miner's from the very first per-miner tick.
    assert sched.miner_plane.chunk_s_overrides[MINER_A] > \
        sched.miner_plane.chunk_s_overrides[MINER_B]
    gauges = sched.metrics.snapshot()["gauges"]
    assert any(k.startswith("adapt_chunk_s_miner") for k in gauges), \
        sorted(gauges)
    sched._on_drop(MINER_B)
    assert MINER_B not in sched.miner_plane.chunk_s_overrides
    assert MINER_B not in plane.chunk._miners
    gauges = sched.metrics.snapshot()["gauges"]
    assert not any(k.startswith("adapt_chunk_s_miner")
                   and f"miner={MINER_B}" in k for k in gauges), \
        sorted(gauges)


def test_per_miner_unforks_on_reconvergence_and_drains_stale_samples():
    """Code review (ISSUE 14): (a) pre-divergence samples are drained
    every tick, so the first diverged decision runs on FRESH samples,
    not latency/margin history from long-gone chunk sizes; (b) when
    the pool re-converges the forks retire and ``unfork_pending``
    fires exactly once (the scheduler's cue to clear its overrides —
    a stale fork must not shadow the pool-wide knob forever)."""
    clk = FakeClock()
    ctl = _per_miner_ctl(clk)
    # An ancient near-lease-blow sample that must NOT drive the first
    # diverged tick.
    ctl.observe(None, 0.05, force_s=9.0, miner=MINER_A)
    ctl.note_rate_ratio(1.0)
    assert ctl.tick_miners() == {}        # drained, not banked
    ctl.note_rate_ratio(100.0)
    assert ctl.tick_miners() == {}        # no post-divergence samples
    for _ in range(3):
        ctl.observe(None, 0.9, force_s=0.05, miner=MINER_A)
    per = ctl.tick_miners()
    assert MINER_A in per and per[MINER_A] > ctl.aimd.value * 0.99
    # Re-convergence retires the fork and signals the clear ONCE.
    ctl.note_rate_ratio(1.5)
    assert ctl.tick_miners() == {}
    assert ctl._miners[MINER_A]["aimd"] is None
    assert ctl.unfork_pending()
    assert not ctl.unfork_pending()


def test_per_miner_scheduler_clears_overrides_on_reconvergence():
    sched = Scheduler(FakeServer(), lease=LeaseParams(),
                      qos=QosParams(),
                      adapt=AdaptParams(enabled=True, tick_s=0.0,
                                        per_miner=True))
    plane = sched.adapt_plane
    sched._on_join(MINER_A)
    sched._on_join(MINER_B)
    sched._find_miner(MINER_A).rate_ewma = 100_000.0
    sched._find_miner(MINER_B).rate_ewma = 1_000.0
    for _ in range(3):
        plane.observe_chunk(None, 0.9, span={"force_s": 0.05},
                            sized=True, miner=MINER_A)
        plane.observe_chunk(None, 0.9, span={"force_s": 3.0},
                            sized=True, miner=MINER_B)
    sched._apply_adapt()
    assert sched.miner_plane.chunk_s_overrides
    # Rates converge: the next tick clears every override + its gauge.
    sched._find_miner(MINER_A).rate_ewma = 1_100.0
    sched._apply_adapt()
    assert sched.miner_plane.chunk_s_overrides == {}
    gauges = sched.metrics.snapshot()["gauges"]
    assert not any(k.startswith("adapt_chunk_s_miner") for k in gauges)


def test_per_miner_gate_ignores_unconfirmed_hints():
    """Code review (ISSUE 14): the divergence gate reads MEASURED
    EWMAs only — a miner's own (unconfirmed) JOIN claim must not fork
    the pool."""
    from distributed_bitcoinminer_tpu.bitcoin.message import new_join
    sched = Scheduler(FakeServer(), lease=LeaseParams(),
                      qos=QosParams(),
                      adapt=AdaptParams(enabled=True, tick_s=0.0,
                                        per_miner=True))
    plane = sched.adapt_plane
    sched._on_join(MINER_A, Message.from_json(
        new_join(rate=10 ** 12).to_json()))       # unconfirmed claim
    sched._on_join(MINER_B)
    sched._find_miner(MINER_B).rate_ewma = 1_000.0
    for _ in range(3):
        plane.observe_chunk(None, 0.9, span={"force_s": 0.05},
                            sized=True, miner=MINER_A)
        plane.observe_chunk(None, 0.9, span={"force_s": 3.0},
                            sized=True, miner=MINER_B)
    sched._apply_adapt()
    assert sched.miner_plane.chunk_s_overrides == {}
    assert not plane.chunk._diverged
