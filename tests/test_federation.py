"""Scheduler federation tests (ISSUE 20).

Four layers:

- RATE WIRE: the Join's Rate extension carrying pool-SUMMED hints — a
  gateway advertises the aggregate of a whole child cluster, so the
  round-trip, the uint64 overflow drop, and the missing-hint fallback
  to cold-EWMA seeding all get pinned at federation magnitudes, plus
  the aggregation helper's cap clamp and quarantine filter.
- REFRESH CONTRACT: ``MinerPlane.refresh_rate_hint`` (the repeat-JOIN
  path ``DBM_GATEWAY`` teaches the scheduler): hinted EWMAs replace in
  place, MEASURED EWMAs survive anything short of a 2x divergence,
  trust scales the applied hint, and the scheduler/replica routing —
  repeat JOIN updates the existing roster entry (same replica owner)
  instead of registering a duplicate miner; the knob-off leg pins the
  legacy duplicate-registration behavior bit-for-bit.
- GATEWAY E2E on detnet: a real parent scheduler granting to a real
  :class:`GatewayMiner` re-sharding through a real inner scheduler —
  oracle-exact argmin AND difficulty replies through both tiers (the
  bound-quirk translation: a verbatim forward would scan one extra
  nonce and fail the parent's claim check), in-order resubmission
  across a bridge-conn death, and the orphan watchdog surfacing an
  empty child pool as one parent-conn drop.
- KNOB GATE: ``DBM_GATEWAY=0`` refuses to start the gateway role.
"""

import asyncio
from types import SimpleNamespace

import pytest

from distributed_bitcoinminer_tpu.apps.gateway import (GatewayMiner,
                                                       aggregate_rate_hint,
                                                       serve)
from distributed_bitcoinminer_tpu.apps.miner_plane import MinerPlane
from distributed_bitcoinminer_tpu.apps.replicas import ReplicaSet
from distributed_bitcoinminer_tpu.apps.scheduler import Scheduler
from distributed_bitcoinminer_tpu.bitcoin.hash import (hash_op, scan_min,
                                                       scan_until)
from distributed_bitcoinminer_tpu.bitcoin.message import (
    Message, MsgType, new_join, new_request, new_result)
from distributed_bitcoinminer_tpu.lspnet.detnet import DetServer
from distributed_bitcoinminer_tpu.utils.config import (CacheParams,
                                                       CoalesceParams,
                                                       GatewayParams,
                                                       LeaseParams,
                                                       QosParams,
                                                       StripeParams,
                                                       VerifyParams)
from distributed_bitcoinminer_tpu.utils.metrics import Registry


@pytest.fixture(autouse=True)
def _federation_on(monkeypatch):
    """This module tests the federation plane itself, so the knob is
    pinned ON regardless of ambient env — the tier-1 matrix leg runs
    the whole suite under DBM_GATEWAY=0, where a construction-time read
    would silently turn every refresh test into a duplicate-roster
    test. The knob-off tests below re-pin 0 locally (test-level setenv
    wins over this fixture)."""
    monkeypatch.setenv("DBM_GATEWAY", "1")


# ---------------------------------------------------------- rate wire


def test_rate_roundtrip_pool_summed_hint():
    """A federated JOIN carries the SUM of a child pool's EWMAs — pod
    magnitudes (10^9..10^12 nonces/s), far beyond one miner's hint."""
    for pool_sum in (7_300_000_000, 10**12, (1 << 64) - 1):
        msg = Message.from_json(new_join(rate=pool_sum).to_json())
        assert msg.type == MsgType.JOIN
        assert msg.rate == pool_sum


def test_rate_overflow_and_malformed_drop_to_zero():
    """An absurd aggregate (>= 2^64, negative, non-int) is a HINT gone
    wrong, never an error: parsing drops it to 0 = absent."""
    base = new_join(rate=1).to_json().decode()
    assert '"Rate":1' in base
    for bad in (str(1 << 64), str(1 << 80), "-5", '"fast"', "true",
                "3.5", "null"):
        payload = base.replace('"Rate":1', '"Rate":%s' % bad).encode()
        assert Message.from_json(payload).rate == 0


def test_aggregate_rate_hint_sums_clamps_and_filters():
    def miner(rate, quarantined=False):
        return SimpleNamespace(rate_ewma=rate, quarantined=quarantined)

    def sched(*miners):
        return SimpleNamespace(
            miner_plane=SimpleNamespace(miners=list(miners)))

    # Sums across schedulers; quarantined and cold miners contribute 0.
    s1 = sched(miner(1000.0), miner(None), miner(500.0, quarantined=True))
    s2 = sched(miner(250.0))
    assert aggregate_rate_hint([s1, s2]) == 1250.0
    # A wholly-cold pool advertises NO hint (parent cold-seeds stock).
    assert aggregate_rate_hint([sched(miner(None), miner(None))]) == 0.0
    # An absurd sum clamps at the same cap the parent clamps at.
    huge = sched(miner(1e15), miner(1e15))
    assert aggregate_rate_hint([huge]) == MinerPlane.RATE_HINT_CAP


# ------------------------------------------------------ refresh contract


class _PlaneRig:
    """A standalone MinerPlane with recording stubs (the
    test_plane_split idiom, trimmed to what the refresh path needs)."""

    def __init__(self):
        self.counts: dict = {}
        self.plane = MinerPlane(
            Registry(), self._count,
            LeaseParams(grace_s=5.0, floor_s=2.0),
            StripeParams(enabled=False), CoalesceParams(enabled=False),
            write=lambda c, m: None, inflight={},
            trace_get=lambda job: None,
            lease_event=lambda kind, chunk, conn, **info: None,
            dispatch=lambda: None)

    def _count(self, name, n=1):
        self.counts[name] = self.counts.get(name, 0) + n


def test_refresh_replaces_hinted_ewma_in_place():
    rig = _PlaneRig()
    m = rig.plane.on_join(7, rate_hint=1000.0)
    assert m.rate_ewma == 1000.0 and m.rate_hinted
    rig.plane.refresh_rate_hint(m, 2000.0)
    assert m.rate_ewma == 2000.0 and m.rate_hinted
    assert rig.counts["rate_hints_refreshed"] == 1
    assert len(rig.plane.miners) == 1      # refresh, not re-register


def test_refresh_never_overrides_close_measured_rate():
    """A MEASURED EWMA outranks claims: only a >= 2x divergence (either
    direction) lets a fresh hint replace it."""
    rig = _PlaneRig()
    m = rig.plane.on_join(7)
    m.rate_ewma, m.rate_hinted = 1000.0, False
    rig.plane.refresh_rate_hint(m, 1600.0)     # within [0.5x, 2x)
    assert m.rate_ewma == 1000.0 and not m.rate_hinted
    assert "rate_hints_refreshed" not in rig.counts
    rig.plane.refresh_rate_hint(m, 5000.0)     # >= 2x: stale measurement
    assert m.rate_ewma == 5000.0 and m.rate_hinted
    m.rate_ewma, m.rate_hinted = 1000.0, False
    rig.plane.refresh_rate_hint(m, 400.0)      # <= 0.5x: pool shrank
    assert m.rate_ewma == 400.0 and m.rate_hinted


def test_refresh_scales_by_trust_clamps_and_ignores_nonpositive():
    rig = _PlaneRig()
    m = rig.plane.on_join(7, rate_hint=1000.0)
    m.trust = 0.25
    rig.plane.refresh_rate_hint(m, 2000.0)
    assert m.rate_ewma == 500.0                # hint * trust
    rig.plane.refresh_rate_hint(m, 10 * MinerPlane.RATE_HINT_CAP)
    assert m.rate_ewma == MinerPlane.RATE_HINT_CAP * 0.25
    before = m.rate_ewma
    rig.plane.refresh_rate_hint(m, 0.0)        # hintless repeat JOIN
    rig.plane.refresh_rate_hint(m, -3.0)
    assert m.rate_ewma == before


def test_scheduler_repeat_join_refreshes_instead_of_duplicating():
    from tests.test_scheduler_recovery import make_scheduler
    sched, _server = make_scheduler()
    sched._on_join(7, new_join(rate=1000))
    assert len(sched.miners) == 1
    sched._on_join(7, new_join(rate=9000))
    assert len(sched.miners) == 1              # refreshed in place
    assert sched.miners[0].rate_ewma == 9000.0
    assert sched._counters["rate_hints_refreshed"].value == 1


def test_scheduler_repeat_join_legacy_duplicate_with_knob_off(monkeypatch):
    """DBM_GATEWAY=0 pins the pre-federation wire behavior bit-for-bit:
    a repeat JOIN registers again (the legacy duplicate roster entry)."""
    monkeypatch.setenv("DBM_GATEWAY", "0")
    from tests.test_scheduler_recovery import make_scheduler
    sched, _server = make_scheduler()
    sched._on_join(7, new_join(rate=1000))
    sched._on_join(7, new_join(rate=9000))
    assert len(sched.miners) == 2              # legacy: duplicate entry


def test_replicaset_routes_repeat_join_to_owner():
    """The replica tier must route a repeat JOIN to the conn's EXISTING
    owner — re-running the thinnest-slice pick would register the same
    gateway on a second replica."""
    async def scenario():
        server = DetServer()
        rs = ReplicaSet(server, 2, lease=LeaseParams(queue_alarm_s=0.0),
                        cache=CacheParams(), qos=QosParams(enabled=False))
        run_task = asyncio.create_task(rs.run())
        chan = server.connect()
        chan.write(new_join(rate=1000).to_json())
        for _ in range(10):
            await asyncio.sleep(0)
        rosters = {rid: len(rs.replicas[rid].miners) for rid in rs.live}
        assert sum(rosters.values()) == 1
        owner = next(rid for rid, n in rosters.items() if n)
        chan.write(new_join(rate=9000).to_json())
        for _ in range(10):
            await asyncio.sleep(0)
        rosters = {rid: len(rs.replicas[rid].miners) for rid in rs.live}
        assert sum(rosters.values()) == 1      # still ONE roster entry
        assert rosters[owner] == 1             # on the SAME replica
        assert rs.replicas[owner].miners[0].rate_ewma == 9000.0
        run_task.cancel()
    asyncio.run(scenario())


# ------------------------------------------------------- gateway e2e


def _sched_on(server):
    # Verify explicitly ON (claim checks are part of what the e2e tests
    # assert — the ambient matrix env pins DBM_VERIFY=0) and audits
    # explicitly OFF (the dataclass default): an audit re-grants a
    # subwindow to a DISJOINT miner, and these single-miner rigs have
    # none, so the draw would only add nondeterministic log noise.
    return Scheduler(server, lease=LeaseParams(queue_alarm_s=0.0),
                     cache=CacheParams(), qos=QosParams(enabled=False),
                     verify=VerifyParams(enabled=True))


async def _read_result(chan, timeout=5.0):
    async def go():
        while True:
            msg = Message.from_json(await chan.read())
            if msg.type == MsgType.RESULT:
                return msg
    return await asyncio.wait_for(go(), timeout)


async def _connect(server):
    return server.connect()


def _gw(parent_srv, inner_srv, inner, **kw):
    kw.setdefault("hint_s", 0.1)
    kw.setdefault("orphan_s", 5.0)
    return GatewayMiner(
        parent_connect=lambda: _connect(parent_srv),
        bridge_connect=lambda: _connect(inner_srv),
        inner_scheds=[inner],
        params=GatewayParams(enabled=True, min_pool=1, **kw),
        poll_s=0.01, backoff_s=0.05)


async def _child(chan, gate=None):
    """Oracle-exact, until-honoring child miner."""
    chan.write(new_join(rate=1000).to_json())
    while True:
        try:
            payload = await chan.read()
        except Exception:
            return
        msg = Message.from_json(payload)
        if msg.type != MsgType.REQUEST:
            continue
        if gate is not None:
            await gate.wait()
        if msg.target:
            h, n, _found = scan_until(msg.data, msg.lower, msg.upper,
                                      msg.target)
            echo = msg.target
        else:
            h, n = scan_min(msg.data, msg.lower, msg.upper)
            echo = 0
        try:
            chan.write(new_result(h, n, echo).to_json())
        except Exception:
            return


def test_gateway_end_to_end_oracle_exact():
    """Argmin AND difficulty requests through both tiers: the merged
    inner result forwarded upward must survive the parent's claim check
    (the bound-quirk translation) and match the host oracle exactly."""
    async def scenario():
        parent_srv, inner_srv = DetServer(), DetServer()
        parent, inner = _sched_on(parent_srv), _sched_on(inner_srv)
        tasks = [asyncio.create_task(parent.run()),
                 asyncio.create_task(inner.run()),
                 asyncio.create_task(_child(inner_srv.connect()))]
        gw = _gw(parent_srv, inner_srv, inner)
        tasks.append(asyncio.create_task(gw.run_forever()))

        tenant = parent_srv.connect()
        tenant.write(new_request("fed", 0, 199).to_json())
        reply = await _read_result(tenant)
        assert (reply.hash, reply.nonce) == scan_min("fed", 0, 200)

        target = hash_op("fedq", 120) + 1      # nonce 120 qualifies
        tenant.write(new_request("fedq", 0, 199, target).to_json())
        reply = await _read_result(tenant)
        assert (reply.hash, reply.nonce) == scan_until(
            "fedq", 0, 200, target)[:2]

        assert gw.grants_taken >= 2
        assert gw.results_forwarded == gw.grants_taken
        # The parent graded the gateway like any miner: claims checked,
        # none failed — the quirk translation held.
        assert parent._counters["claims_checked"].value >= 2
        assert parent._counters["claims_failed"].value == 0
        for t in tasks:
            t.cancel()
    asyncio.run(scenario())


def test_gateway_bridge_reconnect_resubmits_in_order():
    """Kill the bridge conn while a grant is unanswered: the gateway
    must reconnect, resubmit the pending FIFO, and the tenant still
    sees exactly-once oracle-exact replies in request order."""
    async def scenario():
        parent_srv, inner_srv = DetServer(), DetServer()
        parent, inner = _sched_on(parent_srv), _sched_on(inner_srv)
        gate = asyncio.Event()
        tasks = [asyncio.create_task(parent.run()),
                 asyncio.create_task(inner.run()),
                 asyncio.create_task(_child(inner_srv.connect(), gate))]
        before = set(inner_srv._chans)
        gw = _gw(parent_srv, inner_srv, inner)
        tasks.append(asyncio.create_task(gw.run_forever()))

        tenant = parent_srv.connect()
        tenant.write(new_request("recon", 0, 149).to_json())
        for _ in range(300):
            await asyncio.sleep(0.01)
            if gw._pending:
                break
        assert gw._pending, "grant never reached the gateway"
        bridge = next(iter(set(inner_srv._chans) - before))
        inner_srv.close_conn(bridge)       # bridge dies mid-grant
        gate.set()                         # child may answer now
        reply = await _read_result(tenant)
        assert (reply.hash, reply.nonce) == scan_min("recon", 0, 150)

        tenant.write(new_request("recon2", 0, 99).to_json())
        reply = await _read_result(tenant)
        assert (reply.hash, reply.nonce) == scan_min("recon2", 0, 100)
        for t in tasks:
            t.cancel()
    asyncio.run(scenario())


def test_gateway_orphan_watchdog_drops_parent_conn():
    """An EMPTY inner pool with a grant pending for ``orphan_s`` must
    end the gateway's parent-conn lifetime: the parent sees ONE drop
    and recovers the chunk through the stock re-issue plane."""
    async def scenario():
        parent_srv, inner_srv = DetServer(), DetServer()
        parent, inner = _sched_on(parent_srv), _sched_on(inner_srv)
        never = asyncio.Event()            # child never answers
        child_chan = inner_srv.connect()
        tasks = [asyncio.create_task(parent.run()),
                 asyncio.create_task(inner.run()),
                 asyncio.create_task(_child(child_chan, never))]
        gw = _gw(parent_srv, inner_srv, inner, orphan_s=0.15)
        run_task = asyncio.create_task(gw.run())   # ONE lifetime

        tenant = parent_srv.connect()
        tenant.write(new_request("orphan", 0, 99).to_json())
        for _ in range(300):
            await asyncio.sleep(0.01)
            if gw._pending:
                break
        assert gw._pending, "grant never reached the gateway"
        assert len(parent.miners) == 1
        await child_chan.close()           # the whole child pool dies
        await asyncio.wait_for(run_task, 5.0)
        assert gw.orphan_drops == 1
        for _ in range(100):
            await asyncio.sleep(0.01)
            if not parent.miners:
                break
        assert parent.miners == []         # ONE blown-lease drop upstream
        for t in tasks:
            t.cancel()
    asyncio.run(scenario())


# ---------------------------------------------------------- knob gate


def test_serve_refuses_with_gateway_knob_off():
    with pytest.raises(RuntimeError, match="DBM_GATEWAY=0"):
        asyncio.run(serve("127.0.0.1:1",
                          gateway=GatewayParams(enabled=False)))
