"""dbmcheck — deterministic interleaving explorer tests (ISSUE 8).

Four layers, mirroring the checker's own trust chain:

1. **DetLoop determinism**: the controlled event loop + virtual clock
   reproduce a schedule bit-for-bit from its seed (the golden-replay
   contract every printed repro spec depends on) and explore distinct
   schedules across seeds.
2. **Sensitivity**: the KNOWN-BAD fixture scenarios (a deliberately
   racy mini-scheduler pair) are caught within a fixed seed budget by
   both random walks and bounded DFS, the failing schedule shrinks to
   a minimal trace that still fails, and the shrunk spec replays
   deterministically.
3. **Cleanliness**: the real control-plane scenarios hold every
   invariant over a seeded sweep — the regression pin for the clean
   bill recorded in ``analysis/schedcheck/REPORT.md`` (22k schedules).
4. **Liveness detection**: a scenario that cannot complete is reported
   as a violation, not an infinite loop.

No sockets, no JAX, no wall-clock sleeps: everything runs on the
virtual clock, so the module is fast and schedule-exact.
"""

import asyncio
import random

import pytest

from distributed_bitcoinminer_tpu.analysis import schedcheck
from distributed_bitcoinminer_tpu.analysis.schedcheck import (
    ALL, FIXTURES, SCENARIOS, execute, format_spec, parse_spec, replay,
    run_dfs, run_walks, shrink)
from distributed_bitcoinminer_tpu.analysis.schedcheck.detloop import (
    DetLoop, RandomPicker, virtual_time)
from distributed_bitcoinminer_tpu.analysis.schedcheck.scenario import (
    Ctx, Scenario)


# ------------------------------------------------------------ detloop core

def test_detloop_virtual_clock_drives_timers_and_monotonic():
    loop = DetLoop()
    seen = []

    async def main():
        import time
        await asyncio.sleep(0.5)
        seen.append(("slept", loop.time(), time.monotonic()))

    with loop.running(), virtual_time(loop):
        t = loop.create_task(main())
        status = loop.run_until(t.done, 100, 10.0)
        loop.drain()
    loop.close()
    assert status == "done"
    # Virtual time advanced exactly to the timer, and the patched
    # time.monotonic read the same clock.
    assert seen == [("slept", 0.5, 0.5)]
    assert not loop.exceptions


def test_detloop_to_thread_runs_off_loop():
    loop = DetLoop()
    out = {}

    def job():
        # No running loop on the worker thread: the sanitize
        # assert_off_loop contract holds under the harness.
        try:
            asyncio.get_running_loop()
            out["on_loop"] = True
        except RuntimeError:
            out["on_loop"] = False
        return 42

    async def main():
        out["result"] = await asyncio.to_thread(job)

    with loop.running(), virtual_time(loop):
        t = loop.create_task(main())
        assert loop.run_until(t.done, 100, 10.0) == "done"
        loop.drain()
    loop.close()
    assert out == {"on_loop": False, "result": 42}


# ---------------------------------------------------------- golden replay

def test_golden_replay_seed_reproduces_step_sequence_bit_for_bit():
    """The replay contract: same seed -> the IDENTICAL executed step
    sequence, across independent executions and through the printed
    seed-spec path."""
    for name in ("lease_reissue", "qos_shed", "pipelined_dispatch"):
        first = execute(ALL[name](), 11)
        again = execute(ALL[name](), 11)
        via_spec = replay(f"{name}:rw:11")
        assert first.steps == again.steps == via_spec.steps, name
        assert first.trace == again.trace == via_spec.trace, name
        assert len(first.steps) > 20, f"{name}: suspiciously short"


def test_distinct_seeds_explore_distinct_schedules():
    keys = {execute(ALL["lease_reissue"](), seed).schedule_key()
            for seed in range(12)}
    assert len(keys) >= 10      # near-total schedule diversity


def test_trace_replay_reproduces_its_own_schedule():
    base = execute(ALL["difficulty_prefix"](), 3)
    again = execute(ALL["difficulty_prefix"](), 3,
                    choices=base.choices)
    assert again.steps == base.steps


def test_spec_roundtrip():
    assert parse_spec("qos_shed:rw:42") == ("qos_shed", 42, None)
    assert parse_spec("qos_shed:tr:7:0.2.1") == ("qos_shed", 7, [0, 2, 1])
    assert parse_spec("qos_shed:tr:7:") == ("qos_shed", 7, [])


# ------------------------------------------------- known-bad sensitivity

@pytest.mark.parametrize("fixture", sorted(FIXTURES))
def test_random_walk_catches_known_bad_fixture(fixture):
    """The checker must BITE: each deliberately racy mini-scheduler
    yields a violation within a fixed seed budget (empirical hit rate
    is ~45%/seed; 30 seeds bound the miss chance below 1e-7)."""
    failures = [seed for seed in range(30)
                if execute(ALL[fixture](), seed).failed]
    assert failures, f"{fixture}: no violation in 30 seeds"


@pytest.mark.parametrize("fixture", sorted(FIXTURES))
def test_dfs_catches_known_bad_fixture(fixture):
    st = run_dfs(fixture, seed=0, depth=4, limit=40)
    assert st.failures, f"{fixture}: DFS found no violation in 40 runs"


def test_shrunk_repro_still_fails_and_replays_deterministically():
    failing = next(r for r in (execute(ALL["fixture_double_reply"](), s)
                               for s in range(30)) if r.failed)
    small = shrink(failing)
    assert small.failed
    assert len(small.choices) <= len(failing.choices)
    spec = format_spec(small, shrunk=True)
    rr = replay(spec)
    assert rr.failed and rr.steps == small.steps


def test_shrink_survives_choice_point_collapse():
    """Regression (review round 2): zeroing one choice may CUT whole
    task chains — the kept candidate then has fewer choice points than
    the trace the pass started from, and the shrink walk must re-read
    its bound instead of indexing off the end."""
    class Collapsing(Scenario):
        name = "collapse_fixture"

        def build(self, ctx: Ctx) -> None:
            tasks = []

            async def worker(i):
                await asyncio.sleep(0)
                await asyncio.sleep(0)

            async def canceller():
                # Scheduled early, this erases the workers' remaining
                # steps (and their choice points) from the schedule.
                for t in tasks:
                    t.cancel()

            for i in range(3):
                tasks.append(ctx.spawn(worker(i), client=True))
            ctx.spawn(canceller(), client=True)

        def check(self, ctx: Ctx):
            return ["always fails (shrink-mechanics fixture)"]

    ALL["collapse_fixture"] = Collapsing   # shrink re-instantiates by name
    try:
        for seed in range(6):
            failing = execute(Collapsing(), seed)
            assert failing.failed
            small = shrink(failing)      # must not raise IndexError
            assert small.failed
    finally:
        del ALL["collapse_fixture"]


def test_explicit_trace_results_format_as_trace_specs():
    st = run_dfs("fixture_lost_update", seed=0, depth=4, limit=40)
    failing = st.failures[0]
    spec = format_spec(failing)
    assert ":tr:" in spec            # never a misleading rw: spec
    assert replay(spec).failed


# -------------------------------------------------- real-scenario health

@pytest.mark.parametrize("name", sorted(SCENARIOS))
def test_real_scenario_holds_all_invariants(name):
    """The clean-bill regression pin: a seeded sweep of each real
    scenario (the tier-1 dbmcheck leg runs far more) must hold the
    exactly-once / FIFO / accounting / liveness / sanitizer pack."""
    for seed in range(25):
        result = execute(ALL[name](), seed)
        assert not result.failed, (
            f"{name} seed {seed}: {result.violations} "
            f"(repro: {format_spec(result)})")


def test_walks_report_explored_and_distinct_counts():
    st = run_walks("lease_reissue", 15, seed0=100)
    assert st.explored == 15
    assert len(st.distinct) >= 13
    assert not st.failures


# ------------------------------------------------------ liveness detection

def test_deadlocked_scenario_reported_as_liveness_violation():
    class Deadlock(Scenario):
        name = "deadlock_fixture"

        def build(self, ctx: Ctx) -> None:
            async def waits_forever():
                await asyncio.Future()   # no one will ever resolve it

            ctx.spawn(waits_forever(), client=True)

    result = execute(Deadlock(), 0)
    assert result.failed
    assert any("liveness" in v for v in result.violations)
    assert result.status == "deadlock"


def test_vtime_budget_reported_as_liveness_violation():
    class Spin(Scenario):
        name = "spin_fixture"

        def build(self, ctx: Ctx) -> None:
            async def ticks_forever():
                while True:
                    await asyncio.sleep(60.0)

            ctx.spawn(ticks_forever(), client=True)

    result = execute(Spin(), 0)
    assert result.failed
    assert result.status == "vtime"
