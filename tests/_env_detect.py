"""Environmental-failure detection for tier-1 skips (ISSUE 8 satellite).

Three tests have failed identically since PR 3 on every box shaped like
the CI container, for a reason outside the repo's control: the image
bakes the **libtpu PJRT plugin** (plus the axon TPU runtime) into
site-packages, but no TPU is actually attached. Any FRESH subprocess
that runs jax backend discovery without this test suite's
``JAX_PLATFORMS=cpu`` config pin — the two ``test_multihost``
``jax.distributed`` children, and ``test_utils``' deliberate
bad-platform fallback probe — then attempts libtpu/axon initialization,
which blocks on GCP-metadata / device-tunnel lookups until the caller's
deadline kills it (observed trace: ``gcp_metadata_utils.cc`` /
``env_var_utils.cc`` in the child's stderr after SIGKILL).

The detection below encodes exactly that condition, so the skip applies
on chip-less containers carrying the plugin and nowhere else: on a real
TPU VM a device node (``/dev/accel*`` or ``/dev/vfio/*``) exists and
the tests run; on a box without libtpu the hang cannot happen and the
tests run. The point (ISSUE 8): tier-1 signal becomes violations-only —
a red tier-1 means a real regression, not container weather.
"""

import glob
import importlib.util


def tpu_plugin_without_device() -> bool:
    """True iff the libtpu PJRT plugin is importable but no TPU device
    node is attached — the fresh-subprocess-backend-discovery-hangs
    environment described in the module docstring."""
    if importlib.util.find_spec("libtpu") is None:
        return False
    return not (glob.glob("/dev/accel*") or glob.glob("/dev/vfio/*"))


SKIP_REASON = (
    "environmental (pre-existing since PR 3): libtpu PJRT plugin baked "
    "into the image but no TPU device node (/dev/accel*, /dev/vfio/*) "
    "attached — a fresh subprocess's jax backend discovery (which runs "
    "without this suite's JAX_PLATFORMS=cpu config pin) wedges in "
    "libtpu/axon + GCP-metadata init until the deadline kills it; "
    "detection: tests/_env_detect.tpu_plugin_without_device()")
