"""Device-resident span loop (ISSUE 19): bit-exactness, knob-off
parity, early-exit semantics, and the dbmcheck leg.

The devloop replaces the host-side sub-dispatch chain with one jitted
launch per 10^k block (running (hash, nonce) min threaded as a device
carry, one <= 20-byte host fetch per span). These tests pin:

- argmin bit-exactness vs the host oracle AND the stock path over a
  rem x k x range grid (unaligned bounds, block crossings, tiny tails);
- until (``DBM_DEVLOOP_UNTIL=1``) exact first-*qualifying*-nonce
  semantics — equal to the exhaustive host scan even when the on-device
  predicate exits early, including multi-qualifier and miss
  (argmin-fallback) ranges;
- ``DBM_DEVLOOP=0`` is bit-for-bit stock: stock handle shape, stock
  launch count, stock results (the tier1.sh matrix leg runs the whole
  suite this way);
- the est-seconds mouse floor routes sub-floor chunks to the stock
  path (and the trace ``subs`` stamp follows the route taken);
- the pallas persistent-grid devloop (``DBM_DEVLOOP_PALLAS=1``) under
  the Mosaic interpreter — slow-marked and grid-step budgeted like
  tests/test_pallas.py;
- the mesh devloop: ONE whole-mesh launch per block, parity with the
  stock mesh path and the host oracle;
- the dbmcheck leg: the real MinerWorker pipeline holds every
  invariant when the miner-side searcher is devloop-shaped (opaque
  single-launch handle + ``last_dispatch_subs`` stamping).

Compile budget: jnp signatures are (rem, k, batch, cap) — the grid
reuses batch=64 and a handful of caps so the file stays a few fresh
signatures, not a recompile storm.
"""

import pytest

from distributed_bitcoinminer_tpu.analysis.schedcheck.scenario import (
    oracle_min)
from distributed_bitcoinminer_tpu.analysis.schedcheck.scenarios import (
    _FakeSearcher as _StockFakeSearcher)
from distributed_bitcoinminer_tpu.bitcoin.hash import (hash_op, scan_min,
                                                       scan_until)
from distributed_bitcoinminer_tpu.models import (MeshNonceSearcher,
                                                 NonceSearcher,
                                                 ShardedNonceSearcher)
from distributed_bitcoinminer_tpu.models.miner_model import (
    _MET_LAUNCHES, _DevloopHandle)
from distributed_bitcoinminer_tpu.parallel import make_mesh

# Two rem classes (data length shifts the tail-block layout) x ranges
# hitting several 10^k classes, unaligned bounds, block crossings, and
# a sub-batch tail.
GRID_DATA = ("cmu440", "x" * 21)
GRID_RANGES = (
    (0, 4095),          # aligned start, k ladder from zero
    (997, 3001),        # unaligned bounds, crosses 10^3 boundaries
    (9_990, 10_250),    # crosses the 10^4 block boundary
    (12_000, 12_030),   # sub-batch tail inside one block
)


def _on(monkeypatch, until=False, pallas=False):
    monkeypatch.setenv("DBM_DEVLOOP", "1")
    monkeypatch.setenv("DBM_DEVLOOP_UNTIL", "1" if until else "0")
    monkeypatch.setenv("DBM_DEVLOOP_PALLAS", "1" if pallas else "0")


def _off(monkeypatch):
    monkeypatch.setenv("DBM_DEVLOOP", "0")


# ------------------------------------------------------------ argmin grid

@pytest.mark.parametrize("data", GRID_DATA)
def test_devloop_argmin_bit_exact_grid(data, monkeypatch):
    s = NonceSearcher(data, batch=64)
    for lo, hi in GRID_RANGES:
        _on(monkeypatch)
        got = s.search(lo, hi)
        _off(monkeypatch)
        assert got == s.search(lo, hi), (lo, hi)
        assert got == scan_min(data, lo, hi), (lo, hi)


def test_devloop_handle_is_one_fetch(monkeypatch):
    """The span contract: devloop dispatch returns ONE carry handle of
    <= 20 bytes and one launch per block, however ragged the range."""
    _on(monkeypatch)
    s = NonceSearcher("cmu440", batch=64)
    lo, hi = 997, 3001
    blocks = len(list(s.plan(lo, hi)))
    before = _MET_LAUNCHES.value
    handle = s.dispatch(lo, hi)
    assert isinstance(handle, _DevloopHandle)
    assert _MET_LAUNCHES.value - before == blocks
    assert handle.nbytes <= 20
    assert s.last_dispatch_subs and s.last_dispatch_subs >= blocks
    assert s.finalize(handle, lo) == scan_min("cmu440", lo, hi)


# ------------------------------------------------------------- until grid

@pytest.mark.parametrize("data", GRID_DATA)
def test_devloop_until_bit_exact_grid(data, monkeypatch):
    _on(monkeypatch, until=True)
    s = NonceSearcher(data, batch=64)
    for lo, hi in GRID_RANGES:
        for target in (1 << 59, 1 << 56, 1):   # quick hit, late hit, miss
            assert s.search_until(lo, hi, target) == \
                scan_until(data, lo, hi, target), (lo, hi, target)


def test_devloop_until_early_exit_equals_exhaustive(monkeypatch):
    """First-*qualifying*-nonce semantics: with MANY qualifying nonces
    in range, the early exit must return the lowest-nonce qualifier —
    not the argmin, not a later hit from the exiting sub-window — and
    agree with both the exhaustive host scan and the stock path."""
    data = "cmu440"
    lo, hi = 1_000, 3_500
    hashes = sorted((hash_op(data, n), n) for n in range(lo, hi + 1))
    target = hashes[7][0] + 1          # 8 qualifying nonces in range
    assert sum(1 for n in range(lo, hi + 1)
               if hash_op(data, n) < target) == 8
    want_nonce = min(n for _h, n in hashes[:8])
    _on(monkeypatch, until=True)
    s = NonceSearcher(data, batch=64)
    got = s.search_until(lo, hi, target)
    assert got == (hash_op(data, want_nonce), want_nonce, True)
    assert got == scan_until(data, lo, hi, target)
    _off(monkeypatch)
    assert got == s.search_until(lo, hi, target)


def test_devloop_until_miss_falls_back_to_argmin(monkeypatch):
    _on(monkeypatch, until=True)
    data = "cmu440"
    s = NonceSearcher(data, batch=64)
    assert s.search_until(100, 1_500, 1) == \
        (*scan_min(data, 100, 1_500), False)


def test_devloop_until_hit_in_first_block_skips_later_blocks(monkeypatch):
    """Cross-block pass-through: once the carry records a hit, every
    later launch in the chain must fall straight through (the device-
    side short-circuit) without perturbing the recorded first hit."""
    _on(monkeypatch, until=True)
    data = "cmu440"
    lo, hi = 0, 99_999                 # several chained 10^k blocks
    target = 1 << 56                   # expected hit a few hundred in
    s = NonceSearcher(data, batch=64)
    assert s.search_until(lo, hi, target) == \
        scan_until(data, lo, hi, target)


# -------------------------------------------------------- knob-off parity

def test_knob_off_is_bit_for_bit_stock(monkeypatch):
    """DBM_DEVLOOP=0 must be the stock path: stock handle shape (a list
    of per-sub launches, not a carry), stock launch count (one per pow2
    sub), and stock results. The tier1.sh matrix leg pins the same
    contract suite-wide."""
    _off(monkeypatch)
    s = NonceSearcher("cmu440", batch=64)
    lo, hi = 997, 3001
    subs = sum(len(s._sub_dispatches(plan)) for plan in s.plan(lo, hi))
    before = _MET_LAUNCHES.value
    handle = s.dispatch(lo, hi)
    assert not isinstance(handle, _DevloopHandle)
    assert isinstance(handle, list) and len(handle) == subs
    assert _MET_LAUNCHES.value - before == subs
    assert s.last_dispatch_subs is None
    assert s.finalize(handle, lo) == scan_min("cmu440", lo, hi)


def test_sharded_searcher_never_devloops(monkeypatch):
    """ShardedNonceSearcher pins ``_supports_devloop`` off (a devloop
    there would scan ONE device's share); only the mesh model re-enables
    it with a whole-mesh loop. Pin the routing."""
    _on(monkeypatch)
    s = ShardedNonceSearcher("cmu440", batch=64, mesh=make_mesh(4))
    assert not s._supports_devloop
    handle = s.dispatch(0, 4_095)
    assert not isinstance(handle, _DevloopHandle)
    assert s.finalize(handle, 0) == scan_min("cmu440", 0, 4_095)


def test_mouse_below_est_floor_takes_stock_path(monkeypatch):
    """The est-seconds amortization floor: with an observed rate making
    the chunk estimate fall under _DEVLOOP_MIN_EST_S, dispatch must
    route to the stock path — and the trace stamp must follow the route
    taken, not the knob."""
    _on(monkeypatch)
    s = NonceSearcher("cmu440", batch=64)
    s._devloop_nps = 1e12              # everything estimates ~0 s
    handle = s.dispatch(1_000, 1_200)
    assert not isinstance(handle, _DevloopHandle)
    assert s.last_dispatch_subs is None
    assert s.finalize(handle, 1_000) == scan_min("cmu440", 1_000, 1_200)
    s._devloop_nps = 1.0               # everything estimates huge
    handle = s.dispatch(1_000, 1_200)
    assert isinstance(handle, _DevloopHandle)
    assert s.last_dispatch_subs
    assert s.finalize(handle, 1_000) == scan_min("cmu440", 1_000, 1_200)


# ------------------------------------------------------------- mesh plane

def test_mesh_devloop_whole_mesh_one_launch_per_block(monkeypatch):
    _on(monkeypatch)
    data = "cmu440"
    m = MeshNonceSearcher(data, batch=64, mesh=make_mesh(4))
    lo, hi = 997, 3001
    blocks = len(list(m.plan(lo, hi)))
    before = _MET_LAUNCHES.value
    handle = m.dispatch(lo, hi)
    assert isinstance(handle, _DevloopHandle)
    assert _MET_LAUNCHES.value - before == blocks
    got = m.finalize(handle, lo)
    assert got == scan_min(data, lo, hi)
    _off(monkeypatch)
    assert got == m.search(lo, hi)


def test_mesh_devloop_until_parity(monkeypatch):
    _on(monkeypatch, until=True)
    data = "cmu440"
    m = MeshNonceSearcher(data, batch=64, mesh=make_mesh(4))
    target = 1 << 56
    assert m.search_until(0, 9_999, target) == \
        scan_until(data, 0, 9_999, target)
    assert m.search_until(100, 1_500, 1) == \
        (*scan_min(data, 100, 1_500), False)


# ------------------------------------------- pallas tier (interpret, slow)

@pytest.mark.slow
def test_pallas_devloop_argmin_interpret(monkeypatch):
    _on(monkeypatch, pallas=True)
    data = "cmu440"
    s = NonceSearcher(data, batch=128, tier="pallas")
    lo, hi = 2_000, 2_511              # few grid steps under interpret
    got = s.search(lo, hi)
    assert got == scan_min(data, lo, hi)
    _off(monkeypatch)
    assert got == s.search(lo, hi)


@pytest.mark.slow
def test_pallas_devloop_until_interpret(monkeypatch):
    _on(monkeypatch, until=True, pallas=True)
    data = "cmu440"
    s = NonceSearcher(data, batch=128, tier="pallas")
    target = 1 << 59                   # ~1-in-32 per nonce: certain hit
    got = s.search_until(2_000, 2_511, target)
    assert got == scan_until(data, 2_000, 2_511, target)
    assert not s._until_degraded


# ------------------------------------------------------------ dbmcheck leg

class _DevloopFakeSearcher(_StockFakeSearcher):
    """Devloop-shaped two-phase searcher for the schedcheck harness:
    dispatch charges ONE launch enqueue (a fixed cost, however many
    sub-windows the span covers), returns an opaque carry handle, and
    stamps ``last_dispatch_subs`` the way the real devloop dispatch
    does — so the MinerWorker's single-fetch finalize shape and trace-
    stamp read run under the deterministic explorer."""

    def __init__(self, *args, **kwargs):
        super().__init__(*args, **kwargs)
        self.last_dispatch_subs = None

    def dispatch(self, lower, upper):
        if lower > upper:
            raise ValueError("empty range")
        self._charge(64, frac=0.2)              # one enqueue, size-free
        self.last_dispatch_subs = max(1, (upper - lower + 64) // 64)
        return ("carry", lower, upper)

    def finalize(self, handle, lower):
        _tag, lo, up = handle
        self._charge(up - lo + 1)               # the single carry force
        return oracle_min(self.data, lo, up)


@pytest.mark.parametrize("name", ("pipelined_dispatch",
                                  "difficulty_prefix"))
def test_dbmcheck_scenarios_hold_with_devloop_searcher(name, monkeypatch):
    """The control-plane invariant pack (exactly-once, per-miner result
    order, accounting, liveness) must hold when the miner-side searcher
    is devloop-shaped — the pipeline sees one opaque handle per span
    instead of a per-sub list, and in-order finalize semantics must
    survive that. difficulty_prefix rides along unpatched as the until-
    contract control leg."""
    from distributed_bitcoinminer_tpu.analysis.schedcheck import (
        ALL, execute, format_spec)
    from distributed_bitcoinminer_tpu.analysis.schedcheck import scenarios
    monkeypatch.setattr(scenarios, "_FakeSearcher", _DevloopFakeSearcher)
    for seed in range(8):
        result = execute(ALL[name](), seed)
        assert not result.failed, (
            f"{name} seed {seed}: {result.violations} "
            f"(repro: {format_spec(result)})")
