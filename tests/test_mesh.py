"""ISSUE 14 mesh plane: carry-chained whole-mesh spans, the partition-
rule table, the one-pair-per-span host-crossing contract, the
``DBM_MESH=0`` parity pin, and the rate-hint JOIN (wire bytes + EWMA
seeding/decay/confirmation).

The acceptance grid: mesh-tier spans bit-exact vs the single-device
oracle across rem x k x device-count — including difficulty/until mode
— with exactly ONE ``(hash, nonce)`` pair crossing the host per
whole-mesh span (device-transfer + launch-count pins).
"""

from __future__ import annotations

import jax
import numpy as np
import pytest

from distributed_bitcoinminer_tpu.bitcoin.hash import hash_op, scan_min
from distributed_bitcoinminer_tpu.bitcoin.message import (Message,
                                                          new_join)
from distributed_bitcoinminer_tpu.models import (MeshNonceSearcher,
                                                 NonceSearcher,
                                                 ShardedNonceSearcher)
from distributed_bitcoinminer_tpu.parallel import make_mesh
from distributed_bitcoinminer_tpu.parallel.partition import (
    MESH_PARTITION_RULES, device_windows, match_partition_rules,
    pow2_subs)


@pytest.fixture(scope="module")
def mesh8():
    assert len(jax.devices()) == 8, "conftest must force 8 virtual devices"
    return make_mesh()


# ------------------------------------------------------ partition table

def test_partition_rules_place_windows_sharded_rest_replicated():
    from jax.sharding import PartitionSpec as P
    ops = {"carry": np.zeros(5, np.uint32),
           "midstate": np.zeros(8, np.uint32),
           "template": np.zeros((2, 16), np.uint32),
           "base_hi": np.uint32(0), "base_lo": np.uint32(0),
           "i0_d": np.zeros(8, np.uint32),
           "lo_d": np.zeros(8, np.uint32),
           "hi_d": np.zeros(8, np.uint32),
           "hoist": {"cw": np.zeros((2, 16), np.uint32),
                     "deep": np.zeros(8, np.uint32)}}
    specs = match_partition_rules(MESH_PARTITION_RULES, ops)
    assert specs["i0_d"] == P("d")
    assert specs["lo_d"] == P("d") and specs["hi_d"] == P("d")
    assert specs["carry"] == P() and specs["template"] == P()
    assert specs["hoist"]["cw"] == P() and specs["hoist"]["deep"] == P()
    # Scalars are never partitioned regardless of rules.
    assert specs["base_hi"] == P()


def test_partition_rules_unmatched_operand_is_an_error():
    with pytest.raises(ValueError, match="no partition rule"):
        match_partition_rules(MESH_PARTITION_RULES,
                              {"mystery": np.zeros(8, np.uint32)})


def test_device_windows_contiguous_even_and_covering():
    for lo, hi, n, batch in ((1003, 2987, 8, 128), (0, 99, 8, 64),
                             (500, 505, 4, 64), (7, 7, 2, 64)):
        i0_d, lo_d, hi_d, steps = device_windows(lo, hi, n, batch)
        lanes = []
        for d in range(n):
            if lo_d[d] > hi_d[d]:
                continue             # empty trailing window
            lanes.extend(range(int(lo_d[d]), int(hi_d[d]) + 1))
            # Aligned start covers the window within the step count.
            assert int(i0_d[d]) % batch == 0 or int(i0_d[d]) == 0
            assert int(i0_d[d]) <= int(lo_d[d])
            assert int(hi_d[d]) - int(i0_d[d]) + 1 <= steps * batch
        assert lanes == list(range(lo, hi + 1))   # exact cover, ordered
    assert pow2_subs(5) == [(0, 4), (4, 1)]
    assert pow2_subs(1) == [(0, 1)]
    assert sum(p for _o, p in pow2_subs(13)) == 13


# --------------------------------------------------- oracle bit-exactness

#: rem varies with the message (prefix length), k/blocks with the range,
#: device counts across the mesh widths; until mode rides the same grid.
#: One batch size for every device test in this module: jit signatures
#: are keyed on (mesh, rem, k, batch, nbatches), so sharing the batch
#: keeps the compile surface — the dominant cost on a CPU box — shared
#: across tests (the full cross product runs under the slow marker).
GRID_DATA = ("cmu440", "a much longer mesh message")
GRID_RANGES = ((0, 4095),            # digit classes 1..4, many blocks
               (990, 10350),         # 10^k block boundary crossing
               (123456, 131071))     # single class, unaligned
BATCH = 128


def _assert_grid(data, n_devices, ranges):
    mesh = make_mesh(n_devices)
    m = MeshNonceSearcher(data, batch=BATCH, mesh=mesh)
    single = NonceSearcher(data, batch=BATCH)
    for lo, hi in ranges:
        got = m.search(lo, hi)
        assert got == single.search(lo, hi)
        assert got == scan_min(data, lo, hi)


@pytest.mark.parametrize("n_devices", (1, 8))
def test_mesh_span_bit_exact_grid(n_devices):
    _assert_grid(GRID_DATA[0], n_devices, GRID_RANGES)


def test_mesh_span_bit_exact_other_rem():
    _assert_grid(GRID_DATA[1], 8, GRID_RANGES[:1])


@pytest.mark.slow
@pytest.mark.parametrize("n_devices", (2, 4))
@pytest.mark.parametrize("data", GRID_DATA)
def test_mesh_span_bit_exact_full_grid(n_devices, data):
    _assert_grid(data, n_devices, GRID_RANGES)


def test_mesh_span_matches_sharded_four_devices():
    data = "cmu440"
    m = MeshNonceSearcher(data, batch=BATCH, mesh=make_mesh(4))
    s = ShardedNonceSearcher(data, batch=BATCH, mesh=make_mesh(4))
    for lo, hi in ((50, 2049), (1357, 1868)):
        assert m.search(lo, hi) == s.search(lo, hi) == scan_min(data, lo,
                                                                hi)


def _assert_until(data, n_devices, targets=3):
    mesh = make_mesh(n_devices)
    m = MeshNonceSearcher(data, batch=BATCH, mesh=mesh)
    single = NonceSearcher(data, batch=BATCH)
    lo, hi = 1000, 1000 + 128 * 8 - 1
    hashes = {n: hash_op(data, n) for n in range(lo, hi + 1)}
    # Hit only late in the window (exercises the min-qualifying merge
    # across interleaved stripe windows), plus the no-hit argmin
    # fallback, plus a first-lane hit.
    cases = (min(h for n, h in hashes.items()
                 if n >= lo + 128 * 6) + 1,
             min(hashes.values()),         # unreachable: argmin
             hashes[lo] + 1)               # immediate first hit
    for target in cases[:targets]:
        assert m.search_until(lo, hi, target) == \
            single.search_until(lo, hi, target)


def test_mesh_until_bit_exact(mesh8):
    _assert_until("shardun", 8)


@pytest.mark.slow
@pytest.mark.parametrize("n_devices", (1, 4))
def test_mesh_until_bit_exact_other_counts(n_devices):
    _assert_until("shardun", n_devices)


def test_mesh_until_multi_block_early_exit():
    data = "cmu440"
    m = MeshNonceSearcher(data, batch=BATCH, mesh=make_mesh(8))
    single = NonceSearcher(data, batch=BATCH)
    lo, hi = 990, 10350
    q = 1500
    target = hash_op(data, q) + 1
    assert m.search_until(lo, hi, target) == \
        single.search_until(lo, hi, target)


# ------------------------------------------- one pair per span (pinned)

def test_mesh_span_single_host_transfer_and_launch_count(monkeypatch,
                                                         mesh8):
    """THE host-crossing contract: a whole-mesh argmin span — however
    many blocks/pow2 subs it decomposes into — costs exactly ONE
    ``jax.device_get`` of the 5-word (20-byte) carry, and the launch
    count equals the pow2-sub total of its blocks (one chained launch
    each, no per-sub partials). This is the STOCK chain contract, so
    the devloop is pinned off; the devloop count — one launch per
    BLOCK — is pinned in test_devloop.py (ISSUE 19)."""
    from distributed_bitcoinminer_tpu.models.miner_model import \
        _MET_LAUNCHES
    monkeypatch.setenv("DBM_DEVLOOP", "0")
    data = "cmu440"
    m = MeshNonceSearcher(data, batch=BATCH, mesh=mesh8)
    calls = []
    orig = jax.device_get
    monkeypatch.setattr(jax, "device_get",
                        lambda x: calls.append(1) or orig(x))
    for lo, hi in ((0, 4095), (990, 10350)):
        # Expected launches: sum of pow2 subs over the span's blocks.
        want_launches = 0
        for plan in m.plan(lo, hi):
            _i0, _lo, _hi, steps = device_windows(
                plan.lo_i, plan.hi_i, m.n_devices, m.batch)
            want_launches += len(pow2_subs(steps))
        calls.clear()
        before = _MET_LAUNCHES.value
        handle = m.dispatch(lo, hi)
        assert int(getattr(handle, "nbytes", 0)) == 20
        got = m.finalize(handle, lo)
        assert got == scan_min(data, lo, hi)
        assert len(calls) == 1
        assert _MET_LAUNCHES.value - before == want_launches


def test_mesh_two_phase_dispatch_finalize_equivalence(mesh8):
    """The miner pipeline's contract: dispatch k+1 before finalize k —
    two overlapped spans must still answer exactly."""
    data = "cmu440"
    m = MeshNonceSearcher(data, batch=BATCH, mesh=mesh8)
    h1 = m.dispatch(0, 2999)
    h2 = m.dispatch(3000, 5999)
    assert m.finalize(h1, 0) == scan_min(data, 0, 2999)
    assert m.finalize(h2, 3000) == scan_min(data, 3000, 5999)
    with pytest.raises(ValueError):
        m.dispatch(10, 9)


# -------------------------------------------------- DBM_MESH=0 parity

def test_factory_mesh_default_and_knob_off(monkeypatch):
    from distributed_bitcoinminer_tpu.apps.miner import \
        default_searcher_factory
    monkeypatch.delenv("DBM_MESH", raising=False)
    monkeypatch.delenv("DBM_COMPUTE", raising=False)
    s = default_searcher_factory("cmu440", batch=BATCH)
    assert type(s) is MeshNonceSearcher
    monkeypatch.setenv("DBM_MESH", "0")
    s0 = default_searcher_factory("cmu440", batch=BATCH)
    assert type(s0) is ShardedNonceSearcher   # stock local-device plane
    assert s.search(100, 4099) == s0.search(100, 4099) \
        == scan_min("cmu440", 100, 4099)


def test_sharded_dispatch_batch_covers_full_rows():
    """Regression (ISSUE 14 fix): the coalescer's row decomposition is
    pinned to the SINGLE-device step. ShardedNonceSearcher inherited
    dispatch_batch but its _sub_dispatches sizes steps for the whole
    mesh (batch x n_devices), so the single-device segmin launch
    scanned only 1/n of each row — wrong argmins whenever the answer
    lay past the first 1/n (reproduced with these exact ranges)."""
    data = "tie hunt"
    for lo, hi in ((1000, 2999), (2000, 2999), (5000, 9999)):
        s = ShardedNonceSearcher(data, batch=64)
        got = s.finalize_batch(s.dispatch_batch([(s, lo, hi)]))[0]
        assert got == scan_min(data, lo, hi)
    m = MeshNonceSearcher(data, batch=64)
    got = m.finalize_batch(m.dispatch_batch([(m, 1000, 2999)]))[0]
    assert got == scan_min(data, 1000, 2999)


# ----------------------------------------------------- rate-hint JOIN

def test_join_wire_bytes_stock_without_hint():
    """Wire-compat pin: a hint-less JOIN is byte-identical to the
    reference encoding — a stock miner joins unchanged."""
    assert new_join().to_json() == \
        b'{"Type":0,"Data":"","Lower":0,"Upper":0,"Hash":0,"Nonce":0}'
    raw = new_join(rate=1_000_000_000).to_json()
    assert b'"Rate":1000000000' in raw
    msg = Message.from_json(raw)
    assert msg.rate == 1_000_000_000
    # A stock parser's view: the extension rides AFTER reference keys.
    assert raw.startswith(
        b'{"Type":0,"Data":"","Lower":0,"Upper":0,"Hash":0,"Nonce":0')


@pytest.mark.parametrize("bad", ('"fast"', "-5", "1.5", "true",
                                 "18446744073709551616"))
def test_join_malformed_rate_drops_to_no_hint(bad):
    raw = ('{"Type":0,"Data":"","Lower":0,"Upper":0,"Hash":0,'
           '"Nonce":0,"Rate":%s}' % bad).encode()
    msg = Message.from_json(raw)
    assert msg.rate == 0              # hint dropped, JOIN still valid
    assert msg.type == 0


class _StubServer:
    def __init__(self):
        self.writes = []

    def write(self, conn_id, payload):
        self.writes.append((conn_id, payload))

    def close_conn(self, conn_id):
        pass


def _mk_sched():
    from distributed_bitcoinminer_tpu.apps.scheduler import Scheduler
    from distributed_bitcoinminer_tpu.utils.config import (AdaptParams,
                                                           LeaseParams,
                                                           QosParams)
    return Scheduler(_StubServer(), lease=LeaseParams(),
                     qos=QosParams(),
                     adapt=AdaptParams(enabled=False))


def test_rate_hint_seeds_bounded_decays_and_confirms():
    from distributed_bitcoinminer_tpu.apps.miner_plane import MinerPlane
    sched = _mk_sched()
    mp = sched.miner_plane
    # Seed through the real JOIN path, bounded at the cap.
    sched._on_join(7, Message.from_json(
        new_join(rate=10 ** 15).to_json()))
    m = sched._find_miner(7)
    assert m.rate_hinted
    assert m.rate_ewma == MinerPlane.RATE_HINT_CAP
    assert mp.pool_rate == MinerPlane.RATE_HINT_CAP  # empty pool seeded
    # Unconfirmed hints decay every sweep.
    before = m.rate_ewma
    mp.decay_rate_hints()
    assert m.rate_ewma == pytest.approx(
        before * MinerPlane.RATE_HINT_DECAY)
    assert mp.pool_rate == m.rate_ewma
    # A real throughput window REPLACES the hint (no blend with the
    # claim) and stops the decay.
    from distributed_bitcoinminer_tpu.apps.miner_plane import Chunk
    import time as _time
    chunk = Chunk(1, "x", 0, 5000, idx=0)
    chunk.assigned_at = _time.monotonic() - 1.0
    chunk.deadline = _time.monotonic() + 100.0
    chunk.lease_started = True
    mp.observe_result(m, chunk)
    assert not m.rate_hinted and not mp._pool_hinted
    assert m.rate_ewma == pytest.approx(5001 / 1.0, rel=0.2)
    v = m.rate_ewma
    mp.decay_rate_hints()
    assert m.rate_ewma == v           # confirmed: no more decay


def test_rate_hint_sizes_first_lease_and_stripes():
    """The point of the hint: a cold 1B-nps miner's FIRST chunks are
    sized and leased for its width — no mouse-chunk warmup."""
    from distributed_bitcoinminer_tpu.apps.miner_plane import Chunk
    sched = _mk_sched()
    mp = sched.miner_plane
    sched._on_join(9, Message.from_json(
        new_join(rate=1_000_000_000).to_json()))
    m = sched._find_miner(9)
    # Stripe plan: a 2-second share at the hinted rate cuts into
    # chunk_s-sized stripes instead of one cold whole-share chunk.
    n = mp.stripe_chunks(m, 2_000_000_000)
    assert n >= 2
    # Lease sized from the hint, not the cold grace.
    lease = mp.lease_for(m, Chunk(1, "x", 0, 1_000_000_000))
    assert lease == pytest.approx(
        max(mp.lease.floor_s, 1.0 * mp.lease.factor), rel=0.01)
    # A hint-less join still takes the stock cold path.
    sched._on_join(10)
    m2 = sched._find_miner(10)
    assert m2.rate_ewma is None and not m2.rate_hinted
