#!/usr/bin/env python
"""Tier-1 mesh smoke leg (ISSUE 14; ``DBM_TIER1_MESH=0`` skips it in
scripts/tier1.sh).

An 8-virtual-device CPU mesh (the ``test_multihost.py`` precedent:
``XLA_FLAGS=--xla_force_host_platform_device_count=8``) registers as ONE
miner against an embedded scheduler over a REAL localhost UDP LSP stack.
The miner measures a startup rate hint (apps/miner.measure_rate_hint)
and joins with it; one elephant request is then served through the
carry-chained mesh plane. Asserted:

- the reply is ORACLE-EXACT (host scan_min);
- the JOIN rate hint seeded the scheduler's per-miner EWMA pre-traffic;
- the whole-mesh span cost exactly ONE device launch (the elephant's
  geometry packs into a single pow2 sub) and exactly ONE host fetch
  (``jax.device_get``) — the "one (hash, nonce) pair crosses the host
  per span" contract.

Exit 0 on success, 1 on any violation.
"""

from __future__ import annotations

import asyncio
import os
import sys
import time

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO)

# Virtual 8-device CPU mesh BEFORE any jax import (conftest precedent).
os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8").strip()
os.environ.setdefault("DBM_HOIST_DEEP", "0")   # cheap-to-compile window
os.environ.setdefault("DBM_METRICS_INTERVAL_S", "0")

#: Elephant geometry: one aligned window whose per-device stripe packs
#: into a SINGLE pow2 launch. lower is batch-aligned; the miner scans
#: upper INCLUSIVE (the reference quirk), so the span is
#: ``upper - lower + 2`` lanes = 8 devices x 14336 lanes, and
#: 14336 + the worst per-device misalignment (2048) = 4 x 4096 steps —
#: exactly one pow2 sub, one launch.
BATCH = 4096
LOWER = 102_400_000                    # multiple of BATCH
SPAN = 8 * 14336                       # 114688 lanes scanned
UPPER = LOWER + SPAN - 2               # client-visible inclusive upper
DATA = "meshsmoke elephant"


async def smoke() -> int:
    import jax
    jax.config.update("jax_platforms", "cpu")
    from distributed_bitcoinminer_tpu.apps.miner import (MinerWorker,
                                                         measure_rate_hint)
    from distributed_bitcoinminer_tpu.bitcoin.message import (Message,
                                                              MsgType,
                                                              new_request)
    from distributed_bitcoinminer_tpu.lsp.client import new_async_client
    from distributed_bitcoinminer_tpu.apps.scheduler import Scheduler
    from distributed_bitcoinminer_tpu.bitcoin.hash import scan_min
    from distributed_bitcoinminer_tpu.lsp.params import Params
    from distributed_bitcoinminer_tpu.lsp.server import new_async_server
    from distributed_bitcoinminer_tpu.models import MeshNonceSearcher
    from distributed_bitcoinminer_tpu.models.miner_model import \
        _MET_LAUNCHES
    from distributed_bitcoinminer_tpu.parallel import make_mesh
    from distributed_bitcoinminer_tpu.utils.config import (LeaseParams,
                                                           host_cache_dir)

    jax.config.update("jax_compilation_cache_dir", host_cache_dir(_REPO))
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)

    if len(jax.devices()) != 8:
        print(f"MESHSMOKE: expected 8 virtual devices, got "
              f"{len(jax.devices())}", file=sys.stderr)
        return 1
    mesh = make_mesh()

    def factory(data, batch=None):
        s = MeshNonceSearcher(data, batch=BATCH, mesh=mesh)
        if not isinstance(s, MeshNonceSearcher):
            raise AssertionError("factory must build the mesh plane")
        return s

    params = Params(epoch_limit=5, epoch_millis=500, window_size=8,
                    max_backoff_interval=2)
    server = await new_async_server(0, params)
    # A cold signature's first jit compile can take tens of seconds on
    # this box; the floor keeps the (hint-shortened) lease from blowing
    # under the compiler rather than under compute.
    sched = Scheduler(server, lease=LeaseParams(grace_s=240.0,
                                                floor_s=240.0))
    sched_task = asyncio.create_task(sched.run())
    worker = None
    try:
        # Measured rate hint (the DBM_RATE_HINT=probe path, run
        # in-process so the smoke sees the same searcher class).
        hint = await asyncio.to_thread(
            measure_rate_hint, factory("meshsmoke probe"))
        if hint <= 0:
            print("MESHSMOKE: rate probe measured nothing",
                  file=sys.stderr)
            return 1
        worker = MinerWorker(f"127.0.0.1:{server.port}", params=params,
                             searcher_factory=factory, rate_hint=hint)
        await worker.join()
        worker_task = asyncio.create_task(worker.run())
        for _ in range(100):
            if sched.miners:
                break
            await asyncio.sleep(0.05)
        if not sched.miners:
            print("MESHSMOKE: miner never joined", file=sys.stderr)
            return 1
        m = sched.miners[0]
        if not (m.rate_hinted and m.rate_ewma and m.rate_ewma > 0):
            print(f"MESHSMOKE: rate hint did not seed the EWMA "
                  f"(ewma={m.rate_ewma}, hinted={m.rate_hinted})",
                  file=sys.stderr)
            return 1

        # Count launches + host fetches across the elephant span.
        fetches = []
        orig_get = jax.device_get

        def counting_get(x):
            fetches.append(1)
            return orig_get(x)

        launches0 = _MET_LAUNCHES.value
        jax.device_get = counting_get
        t0 = time.monotonic()
        try:
            # Raw Request (apps.client.submit always starts at nonce 0;
            # the smoke's one-launch geometry needs the aligned LOWER).
            cli = await new_async_client(f"127.0.0.1:{server.port}",
                                         params)
            cli.write(new_request(DATA, LOWER, UPPER).to_json())
            payload = await asyncio.wait_for(cli.read(), 300)
            await cli.close()
            msg = Message.from_json(payload)
            got = ((msg.hash, msg.nonce)
                   if msg.type == MsgType.RESULT else None)
        finally:
            jax.device_get = orig_get
        launches = _MET_LAUNCHES.value - launches0
        want = scan_min(DATA, LOWER, UPPER + 1)
        if got != want:
            print(f"MESHSMOKE: reply {got} != oracle {want}",
                  file=sys.stderr)
            return 1
        if launches != 1:
            print(f"MESHSMOKE: whole-mesh span cost {launches} device "
                  f"launches (expected exactly 1)", file=sys.stderr)
            return 1
        if len(fetches) != 1:
            print(f"MESHSMOKE: {len(fetches)} host fetches for one "
                  f"mesh span (expected exactly 1 — the one-pair-per-"
                  f"span contract)", file=sys.stderr)
            return 1
        print(f"MESHSMOKE: OK — oracle-exact over {SPAN} lanes, "
              f"1 launch / 1 host fetch per span, rate hint "
              f"{hint:.3g} nps seeded the EWMA "
              f"({time.monotonic() - t0:.1f}s serve)")
        worker_task.cancel()
        return 0
    finally:
        if worker is not None:
            await worker.close()
        sched_task.cancel()
        await server.close()


def main() -> int:
    return asyncio.run(smoke())


if __name__ == "__main__":
    sys.exit(main())
