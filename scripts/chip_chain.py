#!/usr/bin/env python
"""Resumable on-chip evidence chain (VERDICT r4 "next round" task 1).

The axon tunnel to the one real chip flaps between live windows and
blackholes (the round 4-5 outage documented in BASELINE.md): a probe
can succeed at minute 0 and the same process block forever at minute
10. This tool turns BASELINE.md's manual validation ritual --
``pallas_chip_smoke`` -> ``bench.py`` -> ``trace_mfu trace`` ->
``tpu_tune`` -> ``chip_e2e`` -- into ONE resumable command:

* poll the tunnel with the deadlined subprocess probe
  (``utils.config.probe_backend`` -- a wedged backend can never hang
  the chain);
* each time a window opens, run the next unfinished stage as a child
  with its own wall-clock budget;
* validate the stage's OWN output before marking it done: a bench
  line that degraded to the CPU fallback, or a trace with no device
  plane, does not count -- the stage stays pending for the next
  window;
* persist state + raw stage outputs under ``chipruns/`` so the chain
  survives restarts and the artifacts are judge-checkable.

Stage order is priority, not cost: the bench headline is the round's
"Done =" criterion, so it runs right after the cheap smoke gate;
the long tuning sweep goes last.

Usage:
  python scripts/chip_chain.py [--poll SECS] [--max-hours H] [--once]
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import time

_SCRIPTS = os.path.dirname(os.path.abspath(__file__))
_REPO = os.path.dirname(_SCRIPTS)
sys.path.insert(0, _REPO)

RUN_DIR = os.path.join(_REPO, "chipruns")
STATE = os.path.join(RUN_DIR, "chain_state.json")


def _validate_smoke(out: str, rc: int) -> str | None:
    if rc != 0:
        return f"exit {rc}"
    # The smoke runs its correctness legs happily in the Mosaic
    # simulator if the tunnel flapped between our probe and its start;
    # an off-chip pass must NOT mark the hardware gate done.
    from distributed_bitcoinminer_tpu.utils.config import CHIP_PLATFORMS
    if not any(f"platform={p}" in out for p in CHIP_PLATFORMS):
        return "ran off-chip (platform line not a chip)"
    for leg in ("argmin bit-exact", "until bit-exact", "2-block tail",
                "wide-batch"):
        if leg not in out:
            return f"missing leg: {leg}"
    return None


def _bench_obj(out: str) -> dict | None:
    """Last parseable bench JSON line (metric key required): stray
    braces in the merged stderr stream must not shadow or break it."""
    for ln in reversed(out.splitlines()):
        if not ln.startswith("{"):
            continue
        try:
            cand = json.loads(ln)
        except ValueError:
            continue
        if isinstance(cand, dict) and "metric" in cand:
            return cand
    return None


def _validate_bench(out: str, rc: int) -> str | None:
    if rc != 0:
        return f"exit {rc}"
    obj = _bench_obj(out)
    if obj is None:
        return "no bench JSON line"
    # bench.py nests platform under "detail" (bench.py _emit).
    platform = obj.get("detail", {}).get("platform")
    from distributed_bitcoinminer_tpu.utils.config import CHIP_PLATFORMS
    if platform not in CHIP_PLATFORMS:
        return f"platform={platform} (CPU fallback does not count)"
    return None


def _validate_bench_peel(out: str, rc: int) -> str | None:
    """bench-peel is rate evidence for the peeled PALLAS kernel: a run
    where the pallas tier errored out and a fallback tier won would
    still pass the platform check, so require the pallas tier to be the
    winner with no recorded pallas error."""
    err = _validate_bench(out, rc)
    if err is not None:
        return err
    detail = _bench_obj(out).get("detail", {})
    # bench.py emits the self-describing detail.peel flag exactly so this
    # stage can prove the peeled kernel actually ran: if DBM_PEEL were
    # stripped from the child env (this image's sitecustomize already
    # overrides env vars), a rolled-kernel rate would otherwise be
    # recorded as peel evidence and could drive the default flip
    # (ADVICE r5).
    if not detail.get("peel"):
        return "bench did not run the peeled kernel (peel flag absent)"
    if detail.get("tier") != "pallas":
        return f"best tier {detail.get('tier')!r}, not the peeled pallas"
    if "pallas" in detail.get("tier_errors", {}):
        return f"pallas tier errored: {detail['tier_errors']['pallas']}"
    return None


def _last_json_object(out: str) -> dict | None:
    """The last parseable JSON object in a merged stdout+stderr stream.

    The trace report is pretty-printed over many lines, and chip stderr
    noise may contain stray braces before it — anchor at each line that
    *starts* an object, last first, and take the first that parses."""
    decoder = json.JSONDecoder()
    lines = out.splitlines()
    starts = [i for i, ln in enumerate(lines)
              if ln.lstrip().startswith("{")]
    for i in reversed(starts):
        try:
            obj, _ = decoder.raw_decode("\n".join(lines[i:]).lstrip())
        except ValueError:
            continue
        if isinstance(obj, dict):
            return obj
    return None


def _validate_trace(out: str, rc: int) -> str | None:
    if rc != 0:
        return f"exit {rc}"
    obj = _last_json_object(out)
    if obj is None:
        return "no JSON report"
    if "error" in obj:
        return str(obj["error"])
    if not obj.get("kernel_device_ms"):
        return "no device kernel time in trace"
    return None


def _validate_tune(out: str, rc: int) -> str | None:
    if rc != 0:
        return f"exit {rc}"
    from distributed_bitcoinminer_tpu.utils.config import CHIP_PLATFORMS
    if not any(f"platform={p}" in out for p in CHIP_PLATFORMS):
        return "ran off-chip (device line not a chip)"
    for leg in ("vpu_u32_ceiling", "until hit@step0", "2blk rows="):
        if leg not in out:
            return f"missing leg: {leg}"
    return None


def _validate_devloop_smoke(out: str, rc: int) -> str | None:
    """devloop-smoke is hardware evidence for the device-resident span
    loop (ISSUE 19): the jnp legs must be bit-exact ON CHIP with the
    one-launch-per-block counter contract holding; the pallas candidate
    leg is informational and never gates (the DBM_DEVLOOP_PALLAS flip
    is decided from the log, like bench-peel's precondition)."""
    if rc != 0:
        return f"exit {rc}"
    from distributed_bitcoinminer_tpu.utils.config import CHIP_PLATFORMS
    if not any(f"platform={p}" in out for p in CHIP_PLATFORMS):
        return "ran off-chip (platform line not a chip)"
    for leg in ("devloop argmin bit-exact", "one launch per block",
                "devloop until bit-exact", "devloop_vs_stock="):
        if leg not in out:
            return f"missing leg: {leg}"
    return None


def _validate_e2e(out: str, rc: int) -> str | None:
    if rc != 0:
        return f"exit {rc}"
    # Whole-line match: "MISMATCH" contains "MATCH", so a substring
    # count would pass an all-mismatch transcript.
    matches = sum(1 for ln in out.splitlines() if ln.strip() == "MATCH")
    if matches < 2:
        return "missing MATCH (argmin + target legs)"
    return None


def _peel_validated_on_chip() -> str | None:
    """Precondition for bench-peel: the latest smoke artifact must show
    the peel candidate bit-exact on hardware. Returns a skip reason, or
    None to run."""
    import glob
    logs = sorted(glob.glob(os.path.join(RUN_DIR, "smoke_*.log")))
    if not logs:
        return "no smoke artifact yet"
    with open(logs[-1]) as fh:
        out = fh.read()
    # The same log must show the run was ON CHIP (mirrors _validate_smoke):
    # today the smoke returns before the candidate leg when off-chip, but
    # this gate must not depend on that ordering surviving a refactor
    # (ADVICE r5) — a simulator 'peel candidate ok' is not hardware
    # evidence.
    from distributed_bitcoinminer_tpu.utils.config import CHIP_PLATFORMS
    if not any(f"platform={p}" in out for p in CHIP_PLATFORMS):
        return "latest smoke artifact ran off-chip"
    if "peel candidate ok" not in out:
        return "smoke's peel candidate leg did not validate"
    return None


PY = sys.executable
# Plain stages pin DBM_PEEL=0 so an ambient operator pin can't silently
# turn the headline artifacts into peel measurements (the smoke manages
# the variable itself, but the pin is harmless there too).
_DEFAULT_ENV = {"DBM_PEEL": "0"}
STAGES = [
    # (name, argv, budget_s, validator[, env, precondition])
    ("smoke", [PY, os.path.join(_SCRIPTS, "pallas_chip_smoke.py")],
     900, _validate_smoke, _DEFAULT_ENV),
    ("bench", [PY, os.path.join(_REPO, "bench.py")], 2400, _validate_bench,
     _DEFAULT_ENV),
    ("trace", [PY, os.path.join(_SCRIPTS, "trace_mfu.py"), "trace", "29"],
     2400, _validate_trace, _DEFAULT_ENV),
    ("tune", [PY, os.path.join(_SCRIPTS, "tpu_tune.py"), "29"],
     3600, _validate_tune, _DEFAULT_ENV),
    ("e2e", [PY, os.path.join(_SCRIPTS, "chip_e2e.py")], 1800,
     _validate_e2e, _DEFAULT_ENV),
    # Device-resident span loop evidence (ISSUE 19): jnp devloop legs
    # bit-exact on chip + the launch-counter contract + the on-chip
    # devloop-vs-stock rate A/B; the pallas-devloop candidate leg in the
    # same log is what a DBM_DEVLOOP_PALLAS default flip is decided from.
    ("devloop-smoke", [PY, os.path.join(_SCRIPTS, "devloop_chip_smoke.py")],
     900, _validate_devloop_smoke, _DEFAULT_ENV),
    # The peel-candidate bench: only after the smoke proved the peeled
    # kernel bit-exact ON CHIP (skipped — recorded as such — otherwise).
    # Its artifact is the rate evidence for flipping peel_enabled's
    # default; the plain bench above stays the round's headline.
    ("bench-peel", [PY, os.path.join(_REPO, "bench.py")], 2400,
     _validate_bench_peel, {"DBM_PEEL": "1"}, _peel_validated_on_chip),
]


def _load_state() -> dict:
    try:
        with open(STATE) as fh:
            return json.load(fh)
    except (OSError, ValueError):
        return {}


def _save_state(state: dict) -> None:
    os.makedirs(RUN_DIR, exist_ok=True)
    tmp = STATE + ".tmp"
    with open(tmp, "w") as fh:
        json.dump(state, fh, indent=2)
    os.replace(tmp, STATE)


def _window_open(deadline_s: float) -> bool:
    from distributed_bitcoinminer_tpu.utils.config import (CHIP_PLATFORMS,
                                                           probe_backend)
    probe = probe_backend(deadline_s, _REPO)
    ok = probe.get("platform") in CHIP_PLATFORMS
    print(f"[chain] probe: {probe if not ok else probe['platform']}",
          flush=True)
    return ok


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--poll", type=float, default=180.0,
                    help="seconds between tunnel probes while closed")
    ap.add_argument("--probe-deadline", type=float, default=150.0)
    ap.add_argument("--max-hours", type=float, default=9.0)
    ap.add_argument("--once", action="store_true",
                    help="single pass: probe once, run what fits, exit")
    args = ap.parse_args()

    t_end = time.time() + args.max_hours * 3600
    state = _load_state()
    while time.time() < t_end:
        pending = [s for s in STAGES if not state.get(s[0], {}).get("done")]
        if not pending:
            print("[chain] all stages done", flush=True)
            return 0
        stage = pending[0]
        name, argv, budget, validate = stage[:4]
        env_extra = stage[4] if len(stage) > 4 else None
        precond = stage[5] if len(stage) > 5 else None
        if precond is not None:
            # Decided from local files only — never burn (or wait for) a
            # chip window on a stage that is going to be skipped.
            reason = precond()
            if reason is not None:
                state[name] = {"done": True, "skipped": reason}
                _save_state(state)
                print(f"[chain] stage {name} SKIPPED: {reason}", flush=True)
                continue
        if not _window_open(args.probe_deadline):
            if args.once:
                return 3
            time.sleep(args.poll)
            continue
        print(f"[chain] window open -> stage {name} "
              f"(budget {budget}s)", flush=True)
        t0 = time.time()
        # Own process group per stage: chip_e2e spawns a server + miner
        # and kills them in its finally block, which a SIGKILL on
        # timeout would skip — killpg reaps the whole tree so a wedged
        # stage can't leave an orphan bound to the e2e port poisoning
        # every later retry.
        proc = subprocess.Popen(argv, stdout=subprocess.PIPE,
                                stderr=subprocess.STDOUT, text=True,
                                cwd=_REPO, start_new_session=True,
                                env=(dict(os.environ, **env_extra)
                                     if env_extra else None))
        try:
            out, _ = proc.communicate(timeout=budget)
            rc = proc.returncode
        except subprocess.TimeoutExpired:
            import signal
            try:
                os.killpg(os.getpgid(proc.pid), signal.SIGKILL)
            except (ProcessLookupError, PermissionError):
                pass
            out, _ = proc.communicate()
            out = (out or "") + f"\n[chain] TIMEOUT after {budget}s"
            rc = -1
        wall = time.time() - t0
        os.makedirs(RUN_DIR, exist_ok=True)
        # Timestamped, append-only — a later (possibly off-chip-flap)
        # retry must not destroy the artifact of an earlier attempt.
        stamp = time.strftime("%Y%m%dT%H%M%SZ", time.gmtime())
        log = os.path.join(RUN_DIR, f"{name}_{stamp}.log")
        with open(log, "w") as fh:
            fh.write(out)
        if rc == -1:
            err = "timeout"
        else:
            try:
                err = validate(out, rc)
            except Exception as exc:  # malformed stage output = not done
                err = f"validator: {exc!r}"
        if err is None:
            state[name] = {"done": True, "wall_s": round(wall, 1),
                           "log": os.path.basename(log),
                           "ts": time.strftime("%Y-%m-%dT%H:%M:%SZ",
                                               time.gmtime())}
            print(f"[chain] stage {name} DONE in {wall:.0f}s -> {log}",
                  flush=True)
        else:
            state.setdefault(name, {})["last_error"] = err
            print(f"[chain] stage {name} FAILED ({err}) after {wall:.0f}s; "
                  "will retry next window", flush=True)
            if not args.once:
                time.sleep(args.poll)
        _save_state(state)
        if args.once and (err is not None or
                          all(state.get(s[0], {}).get("done")
                              for s in STAGES)):
            return 0 if err is None else 4
    print("[chain] max-hours budget exhausted", flush=True)
    return 5


if __name__ == "__main__":
    raise SystemExit(main())
