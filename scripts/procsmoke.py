#!/usr/bin/env python
"""Tier-1 multi-process smoke leg (ISSUE 12; ``DBM_TIER1_PROCS=0``
skips it in scripts/tier1.sh).

Spawns the REAL process topology on localhost — router + 2 replica
processes (each with its own LSP socket) + 1 miner agent — drives one
replica-aware client through ``ring:<statedir>``, then ``kill -9``\\ s
the replica that owns the in-flight request and asserts the reply still
arrives EXACTLY ONCE and ORACLE-EXACT, with failover driven solely by
the router's missed-beat detection (no test-hook kill path exists in
this topology). Exit 0 on success, 1 on any violation.
"""

from __future__ import annotations

import asyncio
import os
import shutil
import sys
import tempfile
import time

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO)


async def smoke() -> int:
    from distributed_bitcoinminer_tpu.apps.client import submit_with_retry
    from distributed_bitcoinminer_tpu.apps.procs import (ProcCluster,
                                                         resolve_owner)
    from distributed_bitcoinminer_tpu.bitcoin.hash import scan_min
    from distributed_bitcoinminer_tpu.lsp.params import Params
    from distributed_bitcoinminer_tpu.utils.config import RetryParams

    statedir = tempfile.mkdtemp(prefix="dbm_procsmoke_")
    env = {"DBM_HEALTH_BEAT_S": "0.15", "DBM_HEALTH_MISS_K": "3",
           "DBM_EPOCH_MILLIS": "200", "DBM_EPOCH_LIMIT": "4",
           "DBM_COMPUTE": "host"}
    params = Params(epoch_limit=4, epoch_millis=200, window_size=8,
                    max_backoff_interval=2)
    cluster = ProcCluster(statedir, replicas=2, miners=1, env=env)
    cluster.start()
    try:
        await cluster.wait_live(2, timeout_s=30.0, miners=1)
        # Warm sanity: one small request end to end.
        retry = RetryParams(attempts=12, timeout_s=3.0, backoff_s=0.2,
                            backoff_cap_s=1.0)
        got = await asyncio.wait_for(submit_with_retry(
            f"ring:{statedir}", "procsmoke warm", 499, 0, params, retry),
            40)
        want = scan_min("procsmoke warm", 0, 500)
        if got is None or got[:2] != want:
            print(f"PROCSMOKE: warm request wrong: {got} != {want}",
                  file=sys.stderr)
            return 1
        # The headline: kill -9 the owner mid-request.
        owner = resolve_owner(statedir, "procsmoke kill")
        assert owner is not None
        rid, _ = owner
        t0 = time.monotonic()
        task = asyncio.create_task(submit_with_retry(
            f"ring:{statedir}", "procsmoke kill", 2_500_000, 0, params,
            RetryParams(attempts=20, timeout_s=3.0, backoff_s=0.2,
                        backoff_cap_s=1.0)))
        await asyncio.sleep(0.3)          # the request is in flight
        if not cluster.kill_replica(rid):
            print("PROCSMOKE: could not SIGKILL the owner replica",
                  file=sys.stderr)
            return 1
        got = await asyncio.wait_for(task, 90)
        want = scan_min("procsmoke kill", 0, 2_500_001)
        if got is None or got[:2] != want:
            print(f"PROCSMOKE: post-kill reply wrong: {got} != {want}",
                  file=sys.stderr)
            return 1
        m = cluster.membership()
        if m is None or rid in m.live or rid not in m.fenced:
            print(f"PROCSMOKE: killed replica never fenced: "
                  f"{m and m.to_dict()}", file=sys.stderr)
            return 1
        print(f"PROCSMOKE: ok — kill -9 of replica {rid} mid-request "
              f"recovered oracle-exact in {time.monotonic() - t0:.1f}s "
              f"(membership epoch {m.epoch})", flush=True)
        return 0
    finally:
        cluster.close()
        shutil.rmtree(statedir, ignore_errors=True)


def main() -> int:
    try:
        return asyncio.run(asyncio.wait_for(smoke(), 150))
    except (asyncio.TimeoutError, TimeoutError):
        print("PROCSMOKE: timed out", file=sys.stderr)
        return 1


if __name__ == "__main__":
    sys.exit(main())
