#!/usr/bin/env python
"""Tier-1 multi-process smoke leg (ISSUE 12; ``DBM_TIER1_PROCS=0``
skips it in scripts/tier1.sh).

Spawns the REAL process topology on localhost — router + 2 replica
processes (each with its own LSP socket) + 1 miner agent + 1 gateway
agent (a whole federated child cluster in one process, ISSUE 20) —
drives one replica-aware client through ``ring:<statedir>``, then
``kill -9``\\ s the replica that owns the in-flight request and asserts
the reply still arrives EXACTLY ONCE and ORACLE-EXACT, with failover
driven solely by the router's missed-beat detection (no test-hook kill
path exists in this topology). Exit 0 on success, 1 on any violation.

ISSUE 18 addition: the observability plane rides the same topology, so
this leg also asserts ``dbmtop --once --json`` sees EVERY live process
(router + both replicas + the miner agent + the gateway agent) with a
fresh rollup snapshot within one beat interval, and — after the kill —
that the dead replica's snapshot reads fenced/stale instead of folding
into cluster totals. Skipped when DBM_ROLLUP=0 in the ambient env (the
knob-off matrix shape).

ISSUE 20 addition: the membership wait requires TWO joined miners —
the flat miner agent plus the gateway's JOIN — so the smoke proves the
federated tier actually registered with the ring (not merely that its
process breathes), and the post-kill recovery runs with a gateway in
the pool eligible for re-granted chunks.
"""

from __future__ import annotations

import asyncio
import os
import shutil
import sys
import tempfile
import time

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO)

from distributed_bitcoinminer_tpu.utils._env import int_env  # noqa: E402

_ROLLUP_ON = int_env("DBM_ROLLUP", 1) != 0


async def _dbmtop_doc(statedir: str) -> dict:
    """One ``dbmtop --once --json`` run as a real subprocess (the exact
    operator entry point, not the library call)."""
    import json
    proc = await asyncio.create_subprocess_exec(
        sys.executable, os.path.join(_REPO, "scripts", "dbmtop.py"),
        statedir, "--once", "--json",
        stdout=asyncio.subprocess.PIPE, stderr=asyncio.subprocess.PIPE)
    out, err = await asyncio.wait_for(proc.communicate(), 30)
    if proc.returncode != 0:
        raise RuntimeError(f"dbmtop rc={proc.returncode}: "
                           f"{err.decode(errors='replace')[-500:]}")
    return json.loads(out.decode())


async def _assert_all_fresh(statedir: str, beat_s: float) -> int:
    """Every live process visible and fresh, age within ~a beat.

    Publishers stamp each beat, so a healthy cluster's blob ages sit
    in [0, beat_s) plus write/read jitter; retry a few beats before
    calling it a failure (one slow fsync is not an outage).
    """
    last = None
    for _ in range(8):
        doc = await _dbmtop_doc(statedir)
        procs = doc.get("procs", [])
        fresh = [p for p in procs if p["status"] == "fresh"
                 and p["age_s"] <= beat_s * 2.0]
        roles = sorted(p["role"] for p in fresh)
        if roles.count("replica") >= 2 and "router" in roles \
                and "miner" in roles and "gateway" in roles:
            print(f"PROCSMOKE: dbmtop sees {len(fresh)} fresh procs "
                  f"({'/'.join(roles)}) within a beat", flush=True)
            return 0
        last = [(p["proc"], p["status"], p["age_s"]) for p in procs]
        await asyncio.sleep(beat_s)
    print(f"PROCSMOKE: dbmtop missing fresh procs within one beat: "
          f"{last}", file=sys.stderr)
    return 1


async def smoke() -> int:
    from distributed_bitcoinminer_tpu.apps.client import submit_with_retry
    from distributed_bitcoinminer_tpu.apps.procs import (ProcCluster,
                                                         resolve_owner)
    from distributed_bitcoinminer_tpu.bitcoin.hash import scan_min
    from distributed_bitcoinminer_tpu.lsp.params import Params
    from distributed_bitcoinminer_tpu.utils.config import RetryParams

    statedir = tempfile.mkdtemp(prefix="dbm_procsmoke_")
    env = {"DBM_HEALTH_BEAT_S": "0.15", "DBM_HEALTH_MISS_K": "3",
           "DBM_EPOCH_MILLIS": "200", "DBM_EPOCH_LIMIT": "4",
           "DBM_COMPUTE": "host"}
    params = Params(epoch_limit=4, epoch_millis=200, window_size=8,
                    max_backoff_interval=2)
    cluster = ProcCluster(statedir, replicas=2, miners=1, gateways=1,
                          env=env)
    cluster.start()
    try:
        # miners=2: the flat miner agent AND the gateway's federation
        # JOIN must both be in the advertised ring (ISSUE 20).
        await cluster.wait_live(2, timeout_s=30.0, miners=2)
        # Warm sanity: one small request end to end.
        retry = RetryParams(attempts=12, timeout_s=3.0, backoff_s=0.2,
                            backoff_cap_s=1.0)
        got = await asyncio.wait_for(submit_with_retry(
            f"ring:{statedir}", "procsmoke warm", 499, 0, params, retry),
            40)
        want = scan_min("procsmoke warm", 0, 500)
        if got is None or got[:2] != want:
            print(f"PROCSMOKE: warm request wrong: {got} != {want}",
                  file=sys.stderr)
            return 1
        # ISSUE 18: the live console must see every process fresh.
        if _ROLLUP_ON and await _assert_all_fresh(statedir, 0.15):
            return 1
        # The headline: kill -9 the owner mid-request.
        owner = resolve_owner(statedir, "procsmoke kill")
        assert owner is not None
        rid, _ = owner
        t0 = time.monotonic()
        task = asyncio.create_task(submit_with_retry(
            f"ring:{statedir}", "procsmoke kill", 2_500_000, 0, params,
            RetryParams(attempts=20, timeout_s=3.0, backoff_s=0.2,
                        backoff_cap_s=1.0)))
        await asyncio.sleep(0.3)          # the request is in flight
        if not cluster.kill_replica(rid):
            print("PROCSMOKE: could not SIGKILL the owner replica",
                  file=sys.stderr)
            return 1
        got = await asyncio.wait_for(task, 90)
        want = scan_min("procsmoke kill", 0, 2_500_001)
        if got is None or got[:2] != want:
            print(f"PROCSMOKE: post-kill reply wrong: {got} != {want}",
                  file=sys.stderr)
            return 1
        m = cluster.membership()
        if m is None or rid in m.live or rid not in m.fenced:
            print(f"PROCSMOKE: killed replica never fenced: "
                  f"{m and m.to_dict()}", file=sys.stderr)
            return 1
        if _ROLLUP_ON:
            # The dead replica's snapshot must read fenced/stale, not
            # fold silently into cluster totals.
            doc = await _dbmtop_doc(statedir)
            dead = [p for p in doc.get("procs", [])
                    if p["role"] == "replica" and str(p["rid"]) == str(rid)]
            if not dead or dead[0]["status"] not in ("fenced", "stale"):
                print(f"PROCSMOKE: killed replica's rollup snapshot not "
                      f"fenced/stale: {dead}", file=sys.stderr)
                return 1
            print(f"PROCSMOKE: dbmtop flags dead replica {rid} as "
                  f"{dead[0]['status']}", flush=True)
        print(f"PROCSMOKE: ok — kill -9 of replica {rid} mid-request "
              f"recovered oracle-exact in {time.monotonic() - t0:.1f}s "
              f"(membership epoch {m.epoch})", flush=True)
        return 0
    finally:
        cluster.close()
        shutil.rmtree(statedir, ignore_errors=True)


def main() -> int:
    try:
        return asyncio.run(asyncio.wait_for(smoke(), 150))
    except (asyncio.TimeoutError, TimeoutError):
        print("PROCSMOKE: timed out", file=sys.stderr)
        return 1


if __name__ == "__main__":
    sys.exit(main())
