#!/usr/bin/env python
"""dbmtrace — Perfetto export CLI for the cross-process tracing plane.

Two modes (ISSUE 10):

``python scripts/dbmtrace.py convert DUMP... -o trace.json``
    Convert dumped request traces to Chrome trace-event JSON. Inputs are
    files of JSON lines: raw ``RequestTrace.to_dict()`` objects, or log
    lines containing a ``trace dump (...): {...}`` payload (the
    queue-age alarm's output — paste a log file straight in). The output
    loads in ui.perfetto.dev or chrome://tracing: one track per
    process/miner/tenant, request slices, instant fault events, and the
    stitched miner-side phase spans.

``python scripts/dbmtrace.py summarize DUMP_OR_CAPTURE...``
    Text summary straight from a trace dump OR a workload capture file
    (ISSUE 15) — per-phase span medians (count/p50/p90/max) and the
    slowest-request table — without the Perfetto round-trip. Inputs
    auto-detect per line: stitched trace dicts (``convert``'s input
    format) and capture records (``span``/``rep`` lines) both feed the
    same tables.

``python scripts/dbmtrace.py demo -o trace.json``
    Run the acceptance scenario in-process — a mixed-load storm
    (one elephant + a wave of mice, coalescing on, one wedged miner)
    over real localhost LSP — and export the scheduler's stitched
    traces. The printed summary shows a mouse request decomposing into
    scheduler queue -> grant -> miner queue -> shared coalesced launch
    -> force -> reply (shared-launch id visible) and the wedged miner's
    stall attributed to its phase.

No new knobs: the demo forces ``DBM_TRACE=1`` semantics by constructing
its own endpoints in-process with tracing on (run it with ``DBM_TRACE=0``
exported and it refuses — there would be nothing to export).
"""

from __future__ import annotations

import argparse
import asyncio
import json
import os
import sys
import time

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO)

from distributed_bitcoinminer_tpu.utils import trace as tracing  # noqa: E402

_DUMP_MARK = "trace dump ("


def _iter_trace_dicts(path: str):
    """Trace dicts from one file of JSON lines or log lines."""
    with open(path, encoding="utf-8", errors="replace") as fh:
        for line in fh:
            line = line.strip()
            if not line:
                continue
            if _DUMP_MARK in line:
                # Log line: the payload is the JSON object suffix. A
                # truncated/wrapped line (log rotation mid-write) has no
                # payload separator — skip it like any malformed input.
                at = line.find("): ", line.index(_DUMP_MARK))
                if at < 0:
                    continue
                line = line[at + 3:]
            if not line.startswith("{"):
                continue
            try:
                obj = json.loads(line)
            except ValueError:
                continue
            if isinstance(obj, dict) and "events" in obj:
                yield obj


def convert(paths: list, out: str) -> int:
    dicts = [d for p in paths for d in _iter_trace_dicts(p)]
    if not dicts:
        print(f"dbmtrace: no trace dicts found in {paths}",
              file=sys.stderr)
        return 1
    doc = tracing.to_chrome_trace(dicts)
    with open(out, "w", encoding="utf-8") as fh:
        json.dump(doc, fh, sort_keys=True)
    print(f"dbmtrace: {len(dicts)} trace(s) -> {out} "
          f"({len(doc['traceEvents'])} events)")
    return 0


# ---------------------------------------------------------------- summarize


def _iter_records(path: str):
    """Auto-detecting line reader: yields ``("trace", dict)`` for
    stitched trace dicts (incl. ``trace dump (...)`` log lines),
    ``("capture", dict)`` for workload-capture records,
    ``("rollup", dict)`` for cluster rollup documents (ISSUE 18 —
    ``apps.rollup.aggregate`` / ``dbmtop --once --json`` output), and
    ``("blob", dict)`` for raw per-process ``metrics_*.json`` snapshot
    blobs from a cluster state directory."""
    with open(path, encoding="utf-8", errors="replace") as fh:
        for line in fh:
            line = line.strip()
            if not line:
                continue
            if _DUMP_MARK in line:
                at = line.find("): ", line.index(_DUMP_MARK))
                if at < 0:
                    continue
                line = line[at + 3:]
            if not line.startswith("{"):
                continue
            try:
                obj = json.loads(line)
            except ValueError:
                continue
            if not isinstance(obj, dict):
                continue
            if "events" in obj:
                yield "trace", obj
            elif "k" in obj:
                yield "capture", obj
            elif "cluster" in obj and "procs" in obj:
                yield "rollup", obj
            elif "snapshot" in obj and "role" in obj:
                yield "blob", obj


def _pctl(xs: list, q: float) -> float:
    return xs[min(len(xs) - 1, int(q * len(xs)))]


def _print_rollups(rollups: list, blobs: list) -> None:
    """Cluster headline from rollup docs / raw snapshot blobs
    (ISSUE 18). Raw blobs merge here — the aggregate is pure — so a
    state directory's ``metrics_*.json`` files summarize without a
    running cluster."""
    if not rollups and not blobs:
        return
    from distributed_bitcoinminer_tpu.apps.rollup import (hist_quantile,
                                                          merge_snapshots)
    if rollups:
        doc = rollups[-1]            # newest wins: the live headline
        cluster = doc.get("cluster", {})
        procs = doc.get("procs", [])
        fresh = sum(1 for p in procs if p.get("status") == "fresh")
        head = (f"{len(rollups)} rollup doc(s); last: {fresh}/"
                f"{len(procs)} procs fresh")
    else:
        cluster = merge_snapshots(
            (f"{b.get('role')}{b.get('rid')}", b["snapshot"])
            for b in blobs)
        head = f"{len(blobs)} snapshot blob(s) merged"
    counters = cluster.get("counters", {})

    def _fam(family):
        return sum(v for k, v in counters.items()
                   if k == family or k.startswith(family + "{"))

    wait = cluster.get("histograms", {}).get("sched.queue_wait_s")
    p99 = hist_quantile(wait, 0.99) if wait else None
    print(f"rollup: {head}; cluster results_sent="
          f"{_fam('sched.results_sent')} shed={_fam('sched.qos_shed')} "
          f"reissues={_fam('sched.reissues')} queue-wait p99="
          f"{'n/a' if p99 is None else f'{p99}s'}\n")


def summarize(paths: list, top: int) -> int:
    from distributed_bitcoinminer_tpu.utils.trace import SPAN_PHASES
    phases = {}                   # phase -> [seconds]
    slowest = []                  # (elapsed_s, label, detail)
    n_traces = n_spans = 0
    # Verification-tier outcomes (ISSUE 16): tallied from trace event
    # names; the line below only prints when any fired, so stock
    # captures summarize byte-identically to before.
    verif = {"claim_failed": 0, "audit": 0, "audit_passed": 0,
             "audit_failed": 0, "audit_repair": 0}
    # Observability-plane records (ISSUE 18): aggregate rollup docs and
    # raw per-process snapshot blobs both summarize to the same cluster
    # headline; blobs are merged here so
    # ``dbmtrace summarize statedir/metrics_*.json`` works directly.
    rollups, blobs = [], []
    for path in paths:
        for kind, obj in _iter_records(path):
            if kind == "rollup":
                rollups.append(obj)
                continue
            if kind == "blob":
                blobs.append(obj)
                continue
            if kind == "capture":
                k = obj.get("k")
                if k == "span":
                    n_spans += 1
                    for ph in SPAN_PHASES:
                        v = obj.get(ph)
                        if isinstance(v, (int, float)):
                            phases.setdefault(ph, []).append(float(v))
                elif k == "rep" and not obj.get("cached"):
                    slowest.append((float(obj.get("el", 0.0)),
                                    f"tenant {obj.get('ten')}",
                                    f"t={obj.get('t')}"))
                continue
            n_traces += 1
            events = obj.get("events", [])
            reply = next((e for e in events
                          if e.get("event") == "reply"), None)
            worst_phase, worst_v = None, 0.0
            for ev in events:
                name = ev.get("event")
                if name in verif:
                    verif[name] += 1
                elif name == "merge" and ev.get("audit_repair"):
                    verif["audit_repair"] += 1
                if name != "miner_span":
                    continue
                n_spans += 1
                for ph in SPAN_PHASES:
                    v = ev.get(ph)
                    if isinstance(v, (int, float)):
                        phases.setdefault(ph, []).append(float(v))
                        if v > worst_v:
                            worst_phase, worst_v = ph[:-2], float(v)
            if reply is not None and isinstance(
                    reply.get("elapsed_s"), (int, float)):
                meta = obj.get("meta", {})
                label = (f"{obj.get('key')} "
                         f"(tenant {meta.get('client')})")
                detail = (f"slowest phase {worst_phase} {worst_v:.4f}s"
                          if worst_phase else "no spans folded")
                slowest.append((float(reply["elapsed_s"]), label,
                                detail))
    if not phases and not slowest and not rollups and not blobs:
        print("dbmtrace summarize: no spans, replies, or rollup "
              f"snapshots found in {paths}", file=sys.stderr)
        return 1
    print(f"{n_traces} trace(s), {n_spans} span(s), "
          f"{len(slowest)} replied request(s)\n")
    _print_rollups(rollups, blobs)
    if phases:
        print(f"{'phase':<10} {'count':>7} {'p50':>10} {'p90':>10} "
              f"{'max':>10}")
        for ph in SPAN_PHASES:
            xs = sorted(phases.get(ph, ()))
            if not xs:
                continue
            print(f"{ph[:-2]:<10} {len(xs):>7} {_pctl(xs, 0.5):>10.6f} "
                  f"{_pctl(xs, 0.9):>10.6f} {xs[-1]:>10.6f}")
    if any(verif.values()):
        print(f"\nverification: {verif['claim_failed']} claim(s) "
              f"rejected, {verif['audit']} audit(s) issued "
              f"({verif['audit_passed']} passed, "
              f"{verif['audit_failed']} failed, "
              f"{verif['audit_repair']} repair merge(s))")
    if slowest:
        slowest.sort(key=lambda r: -r[0])
        print(f"\nslowest {min(top, len(slowest))} request(s):")
        for elapsed, label, detail in slowest[:top]:
            print(f"  {elapsed:>10.4f}s  {label}  [{detail}]")
    return 0


# --------------------------------------------------------------------- demo


class _DemoSearcher:
    """Host-oracle searcher with the full two-phase + batch surface the
    miner coalescer needs, plus an injectable one-shot FORCE stall (the
    wedged-miner shape: transport heartbeats, compute stuck)."""

    def __init__(self, data: str, wedge_s: float = 0.0):
        from concurrent.futures import ThreadPoolExecutor
        self.data = data
        self._wedge_s = wedge_s
        self._pool = ThreadPoolExecutor(max_workers=2,
                                        thread_name_prefix="demo-scan")

    def search(self, lower: int, upper: int):
        from distributed_bitcoinminer_tpu.bitcoin.hash import scan_min
        return scan_min(self.data, lower, upper)

    def dispatch(self, lower: int, upper: int):
        return self._pool.submit(self.search, lower, upper)

    def finalize(self, handle, lower: int):
        if self._wedge_s:
            stall, self._wedge_s = self._wedge_s, 0.0
            time.sleep(stall)
        return handle.result()

    def dispatch_batch(self, entries: list):
        if not all(isinstance(s, _DemoSearcher) for s, _l, _u in entries):
            return None
        return [s.dispatch(lo, up) for s, lo, up in entries]

    def finalize_batch(self, handle) -> list:
        return [f.result() for f in handle]


async def _demo_run(out: str) -> dict:
    from distributed_bitcoinminer_tpu.apps.miner import MinerWorker
    from distributed_bitcoinminer_tpu.apps.scheduler import Scheduler
    from distributed_bitcoinminer_tpu.bitcoin.message import (Message,
                                                              MsgType,
                                                              new_request)
    from distributed_bitcoinminer_tpu.lsp.client import new_async_client
    from distributed_bitcoinminer_tpu.lsp.params import Params
    from distributed_bitcoinminer_tpu.lsp.server import new_async_server
    from distributed_bitcoinminer_tpu.utils.config import (CacheParams,
                                                           CoalesceParams,
                                                           LeaseParams,
                                                           QosParams)

    from concurrent.futures import ThreadPoolExecutor

    params = Params(epoch_limit=30, epoch_millis=200, window_size=32,
                    max_backoff_interval=2)
    # Clients on a DEDICATED pool (the bench-probe lesson): blocked
    # client threads on the default executor would starve the miners'
    # own to_thread compute — a deadlock, not a demo.
    clients = ThreadPoolExecutor(max_workers=10,
                                 thread_name_prefix="demo-client")
    server = await new_async_server(0, params)
    sched = Scheduler(
        server,
        cache=CacheParams(enabled=False),
        # Tight sub-second leases so the wedged miner is caught (and its
        # chunk re-issued) within the demo's few seconds.
        lease=LeaseParams(grace_s=1.0, factor=4.0, floor_s=0.5,
                          tick_s=0.05, queue_alarm_s=0.0),
        qos=QosParams(enabled=True, wholesale_s=0.15, chunk_s=0.1,
                      max_chunks=16, depth=2),
        coalesce=CoalesceParams(enabled=True, lanes=8,
                                max_nonces=1 << 14))
    sched_task = asyncio.create_task(sched.run())
    hostport = f"127.0.0.1:{server.port}"
    workers, tasks = [], []
    try:
        for wedge_s in (0.0, 0.0, 2.0):     # two healthy + one wedged
            w = MinerWorker(
                hostport, params=params,
                searcher_factory=lambda d, b, _w=wedge_s: _DemoSearcher(
                    d, wedge_s=_w),
                pipeline_depth=16)
            await w.join()
            tasks.append(asyncio.create_task(w.run()))
            workers.append(w)

        def ask(lo: int, count: int):
            async def go():
                client = await new_async_client(hostport, params)
                try:
                    client.write(new_request(
                        "dbmtrace-demo", lo, lo + count - 1).to_json())
                    while True:
                        m = Message.from_json(
                            await asyncio.wait_for(client.read(), 60))
                        if m.type == MsgType.RESULT:
                            return m
                finally:
                    await client.close()
            return asyncio.run(go())

        loop = asyncio.get_running_loop()
        # Warm request: seeds the pool-rate EWMA so the elephant below
        # activates CHUNKED (a cold pool dispatches wholesale by design).
        await loop.run_in_executor(clients, ask, 0, 60_000)
        await asyncio.sleep(0.3)
        # The storm: one elephant (chunked; its chunks cycle through the
        # wedged miner too, whose stalled force blows a lease and gets
        # re-issued) + a simultaneous wave of mice that backlog behind
        # the saturated pool and coalesce into shared launches.
        elephant = loop.run_in_executor(clients, ask, 100_000, 120_000)
        await asyncio.sleep(0.15)
        mice = [loop.run_in_executor(clients, ask, 400_000 + i * 600, 600)
                for i in range(6)]
        await asyncio.gather(elephant, *mice)
        # Drain: let the wedged miner's LATE stale Result arrive so its
        # span (naming the stalled force phase) stitches into the trace.
        await asyncio.sleep(2.2)
        return sched.export_trace(out)
    finally:
        for t in tasks:
            t.cancel()
        for w in workers:
            await w.close()
        sched_task.cancel()
        await server.close()


def demo(out: str) -> int:
    if not tracing.enabled():
        print("dbmtrace: DBM_TRACE=0 — the tracing plane is off, there "
              "would be nothing to export", file=sys.stderr)
        return 1
    doc = asyncio.run(_demo_run(out))
    events = doc["traceEvents"]
    launches = sorted({e["args"].get("launch") for e in events
                       if e.get("args", {}).get("launch") is not None})
    slow = sorted({(e["args"].get("slow"), e["tid"]) for e in events
                   if e.get("args", {}).get("slow")})
    print(f"dbmtrace: demo trace -> {out} ({len(events)} events)")
    print(f"  shared coalesced launches: {launches or 'none'}")
    print(f"  stalled-phase attributions (phase, miner track): "
          f"{slow or 'none'}")
    print("  load it at ui.perfetto.dev (Open trace file)")
    return 0


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="dbmtrace", description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter)
    sub = ap.add_subparsers(dest="cmd", required=True)
    conv = sub.add_parser("convert", help="trace dumps -> Perfetto JSON")
    conv.add_argument("paths", nargs="+")
    conv.add_argument("-o", "--out", default="dbmtrace.json")
    summ = sub.add_parser(
        "summarize",
        help="per-phase medians + slowest requests from dumps/captures")
    summ.add_argument("paths", nargs="+")
    summ.add_argument("--top", type=int, default=10,
                      help="slowest-request table depth (default 10)")
    dm = sub.add_parser("demo", help="run the mixed-load demo + export")
    dm.add_argument("-o", "--out", default="dbmtrace.json")
    args = ap.parse_args(argv)
    if args.cmd == "convert":
        return convert(args.paths, args.out)
    if args.cmd == "summarize":
        return summarize(args.paths, args.top)
    return demo(args.out)


if __name__ == "__main__":
    sys.exit(main())
