#!/usr/bin/env python
"""On-chip smoke for the device-resident span loop (ISSUE 19).

Runs the devloop dispatch path against the stock path and the host
oracle on the default backend: argmin bit-exactness, the one-launch-
per-block counter contract, until (``DBM_DEVLOOP_UNTIL``) hit + miss
legs, an informational pallas-devloop candidate leg, and an on-chip
devloop-vs-stock rate A/B at the wide-batch geometry. Exit 0 = every
gating leg bit-exact; nonzero = failure (error printed).

Off-chip the correctness legs run fine on the CPU backend (the pallas
candidate under the Mosaic interpreter); the rate A/B is skipped —
a CPU ratio is ``bench.py detail.devloop``'s job, with drift-paired
timing this one-shot cannot afford.
"""
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))


def main() -> int:
    import jax

    from distributed_bitcoinminer_tpu.bitcoin.hash import scan_min, \
        scan_until
    from distributed_bitcoinminer_tpu.models import NonceSearcher
    from distributed_bitcoinminer_tpu.models.miner_model import \
        _MET_LAUNCHES
    from distributed_bitcoinminer_tpu.utils.config import (
        apply_jax_platform_env)

    # Honor JAX_PLATFORMS=cpu for off-chip runs (utils.config: a bare
    # jax.devices() hangs forever when the tunnel is blackholed).
    apply_jax_platform_env()

    # The legs manage the devloop knobs themselves; inherited pins would
    # silently turn the stock baselines into devloop-vs-devloop.
    knobs = ("DBM_DEVLOOP", "DBM_DEVLOOP_UNTIL", "DBM_DEVLOOP_PALLAS")
    prior = {k: os.environ.pop(k, None) for k in knobs}
    try:
        print(f"platform={jax.devices()[0].platform}", flush=True)
        data = "cmu440"
        lo, hi = 2_000_000_000, 2_000_009_999

        # Argmin: devloop vs stock vs host oracle, plus the counter
        # contract — exactly one model.device_launches per 10^k block.
        os.environ["DBM_DEVLOOP"] = "1"
        s = NonceSearcher(data, batch=8192, tier="jnp")
        blocks = len(list(s.plan(lo, hi)))
        t0 = time.time()
        l0 = _MET_LAUNCHES.value
        got = s.search(lo, hi)
        launches = _MET_LAUNCHES.value - l0
        print(f"tiny search: {time.time() - t0:.1f}s", flush=True)
        want = scan_min(data, lo, hi)
        os.environ["DBM_DEVLOOP"] = "0"
        stock = s.search(lo, hi)
        if got != want or stock != want:
            print(f"MISMATCH: devloop={got} stock={stock} oracle={want}")
            return 1
        print("devloop argmin bit-exact vs stock + oracle", flush=True)
        if launches != blocks:
            print(f"LAUNCH COUNT: {launches} launches for {blocks} blocks")
            return 1
        print(f"one launch per block ({launches}/{blocks})", flush=True)

        # Until: devloop chain vs oracle, hit + miss legs. The miss leg
        # exercises the full bounded-iterations backstop and the argmin
        # fallback decode; the hit leg the on-device first-hit exit.
        os.environ["DBM_DEVLOOP"] = "1"
        os.environ["DBM_DEVLOOP_UNTIL"] = "1"
        target = 1 << 56
        got_u = s.search_until(lo, hi, target)
        want_u = scan_until(data, lo, hi, target)
        got_m = s.search_until(lo, lo + 999, 1)      # unreachable target
        want_m = scan_until(data, lo, lo + 999, 1)
        if got_u != want_u or got_m != want_m:
            print(f"UNTIL MISMATCH: hit {got_u} != {want_u} or "
                  f"miss {got_m} != {want_m}")
            return 1
        print("devloop until bit-exact vs oracle (hit + miss legs)",
              flush=True)
        os.environ.pop("DBM_DEVLOOP_UNTIL", None)

        # Pallas devloop CANDIDATE (DBM_DEVLOOP_PALLAS rollout knob):
        # informational, never gates — the flip to default-on is decided
        # from this log, the validated jnp legs above are the evidence
        # chain. Off-chip this runs under the Mosaic interpreter.
        try:
            os.environ["DBM_DEVLOOP_PALLAS"] = "1"
            sp = NonceSearcher(data, batch=8192, tier="pallas")
            t0 = time.time()
            gp = sp.search(lo, lo + 4095)
            wp = scan_min(data, lo, lo + 4095)
            if gp != wp:
                print(f"pallas devloop candidate MISMATCH: {gp} != {wp}")
            else:
                print(f"pallas devloop candidate ok "
                      f"({time.time() - t0:.1f}s)", flush=True)
        except Exception as exc:  # noqa: BLE001 — candidate only
            print(f"pallas devloop candidate error: {exc!r}"[:400],
                  flush=True)
        finally:
            os.environ.pop("DBM_DEVLOOP_PALLAS", None)

        from distributed_bitcoinminer_tpu.utils.config import CHIP_PLATFORMS
        if jax.devices()[0].platform not in CHIP_PLATFORMS:
            print("rate leg skipped off-chip", flush=True)
            return 0

        # On-chip rate A/B at the wide-batch bench geometry: the axon
        # tunnel charges ~65 ms per host force, so the per-block launch
        # collapse should show directly here (BENCH_r03's overlapped-vs-
        # serial gap is the same overhead family).
        lo, hi = 2_000_000_000, 2_000_000_000 + (1 << 26) - 1
        rates = {}
        for name, knob in (("devloop", "1"), ("stock", "0")):
            os.environ["DBM_DEVLOOP"] = knob
            sw = NonceSearcher(data, batch=1 << 20, tier="jnp")
            warm = sw.search(lo, hi)
            t0 = time.time()
            timed = sw.search(lo, hi)
            dt = time.time() - t0
            if warm != timed:
                print(f"RATE LEG NONDETERMINISM ({name}): {warm} != {timed}")
                return 1
            rates[name] = (hi - lo + 1) / dt / 1e6
            print(f"rate[{name}]={rates[name]:.1f}M nonces/s ({dt:.2f}s)",
                  flush=True)
        print(f"devloop_vs_stock={rates['devloop'] / rates['stock']:.3f}",
              flush=True)
        return 0
    finally:
        for k, v in prior.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v


if __name__ == "__main__":
    rc = main()
    sys.stdout.flush()
    os._exit(rc)
