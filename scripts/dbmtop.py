#!/usr/bin/env python
"""dbmtop — live cluster console over the rollup plane (ISSUE 18).

``dbmtop <statedir>`` renders the cluster one screen at a time from the
metric snapshot blobs the env-armed processes publish into the
health-beat state directory: cluster totals up top, one row per process
(role, rid, freshness, queue/pool/trust/lease columns), SLO budget bars
from ``apps/slo.py``, and the membership epoch timeline. Freshness is
the rollup plane's rule — seq-advance within the publisher's advertised
beat cadence — so a SIGSTOPped replica shows ``stale`` and a fenced one
``fenced``, never silently averaged into the totals.

Modes:

- ``dbmtop <statedir>`` — curses live view (q quits), refreshed each
  beat interval;
- ``dbmtop --once --json <statedir>`` — print ONE rollup document (plus
  ``slo`` status) as JSON and exit: the scripts/CI surface procsmoke and
  the loadharness gates consume. ``--once`` without ``--json`` prints
  the human screen once (no curses import on this path at all).

Reads files only — attaches to a live cluster, a dead one's litter, or
a copied-away state directory equally well.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO)

from distributed_bitcoinminer_tpu.apps.rollup import (     # noqa: E402
    RollupState, hist_quantile)
from distributed_bitcoinminer_tpu.apps.slo import SloTracker  # noqa: E402

_STATUS_MARK = {"fresh": "ok", "stale": "STALE", "fenced": "FENCED"}


def one_doc(statedir: str, state=None, tracker=None) -> dict:
    """One rollup document with SLO status folded in (the JSON shape)."""
    state = state if state is not None else RollupState(statedir)
    tracker = tracker if tracker is not None else SloTracker()
    doc = state.refresh()
    tracker.observe(doc, now=doc["at"])
    doc["slo"] = tracker.status()
    doc["epochs"] = [{"at": round(t, 3), "epoch": e}
                     for t, e in state.epochs()]
    return doc


def _bar(frac: float, width: int = 20) -> str:
    frac = min(1.0, max(0.0, frac))
    fill = int(round(frac * width))
    return "#" * fill + "-" * (width - fill)


def _fmt(v, nd=1) -> str:
    if v is None:
        return "-"
    if isinstance(v, float):
        return f"{v:.{nd}f}"
    return str(v)


def render(doc: dict) -> list:
    """The screen as a list of plain-text lines (curses and --once share
    it; tests pin it without a terminal)."""
    lines = []
    cluster = doc.get("cluster") or {}
    counters = cluster.get("counters") or {}
    procs = doc.get("procs") or []
    fresh = sum(1 for p in procs if p["status"] == "fresh")
    mem = doc.get("membership") or {}

    def csum(family):
        pref = family + "{"
        return int(sum(v for k, v in counters.items()
                       if k == family or k.startswith(pref)))

    p99 = hist_quantile((cluster.get("histograms") or {})
                        .get("sched.queue_wait_s"), 0.99)
    lines.append(
        f"dbmtop — {doc.get('at', 0):.0f}  procs {fresh}/{len(procs)} "
        f"fresh  epoch {mem.get('epoch', '-')}  sources "
        f"{cluster.get('sources', 0)}  overflow "
        f"{cluster.get('series_overflow', 0)}")
    lines.append(
        f"cluster: results {csum('sched.results_sent')}  shed "
        f"{csum('sched.qos_shed')}  grants {csum('sched.qos_grants')}  "
        f"reissues {csum('sched.reissues')}  leases_blown "
        f"{csum('sched.leases_blown')}  queue-wait p99 "
        f"{_fmt(p99, 3)}s")
    lines.append("")
    lines.append(f"{'PROC':<12} {'STATUS':<7} {'AGE':>6} {'SEQ':>6} "
                 f"{'EPOCH':>5} {'QUEUE':>6} {'POOL':>5} {'TRUST':>6} "
                 f"{'LEASE_S':>8} {'SHED':>7} {'RESULTS':>8} "
                 f"{'NPS':>10}")
    for p in procs:
        d = p.get("detail") or {}
        lines.append(
            f"{p['proc']:<12} {_STATUS_MARK.get(p['status'], '?'):<7} "
            f"{p['age_s']:>6.2f} {p['seq']:>6d} {p['epoch_seen']:>5d} "
            f"{_fmt(d.get('queue'), 0):>6} {_fmt(d.get('pool'), 0):>5} "
            f"{_fmt(d.get('trust_min'), 2):>6} "
            f"{_fmt(d.get('lease_min_s'), 1):>8} "
            f"{_fmt(d.get('shed'), 0):>7} {_fmt(d.get('results'), 0):>8} "
            f"{_fmt(d.get('nps'), 0):>10}")
    lines.append("")
    for s in doc.get("slo") or []:
        frac = s.get("error_frac_long")
        used = 0.0 if frac is None else frac / s["budget"]
        mark = "BURN" if s.get("burning") else "ok"
        worst = s.get("worst")
        lines.append(
            f"slo {s['objective']:<19} [{_bar(1.0 - used)}] "
            f"budget left {max(0.0, 1.0 - used) * 100:5.1f}%  "
            f"burn {_fmt(s.get('burn_short'), 2)}x/"
            f"{_fmt(s.get('burn_long'), 2)}x  {mark}"
            + (f"  worst={worst}" if worst else ""))
    epochs = doc.get("epochs") or []
    if epochs:
        tail = epochs[-8:]
        stamps = "  ".join(f"e{e['epoch']}@{e['at'] % 1000:.1f}s"
                           for e in tail)
        lines.append("")
        lines.append(f"epochs: {stamps}")
    return lines


def _live(statedir: str, interval_s: float) -> int:
    import curses

    state, tracker = RollupState(statedir), SloTracker()

    def loop(scr):
        curses.curs_set(0)
        scr.nodelay(True)
        while True:
            doc = one_doc(statedir, state, tracker)
            scr.erase()
            maxy, maxx = scr.getmaxyx()
            for i, line in enumerate(render(doc)[:maxy - 1]):
                scr.addnstr(i, 0, line, maxx - 1)
            scr.refresh()
            t_next = time.monotonic() + interval_s
            while time.monotonic() < t_next:
                ch = scr.getch()
                if ch in (ord("q"), ord("Q")):
                    return 0
                time.sleep(0.05)

    return curses.wrapper(loop)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="live cluster console over the rollup plane")
    ap.add_argument("statedir", help="cluster state directory")
    ap.add_argument("--once", action="store_true",
                    help="render one frame and exit (no curses)")
    ap.add_argument("--json", action="store_true",
                    help="with --once: print the rollup document as JSON")
    ap.add_argument("--interval", type=float, default=None,
                    help="refresh seconds (default: largest publisher "
                         "beat period seen, min 0.5)")
    args = ap.parse_args(argv)
    if not os.path.isdir(args.statedir):
        print(f"dbmtop: no such state directory: {args.statedir}",
              file=sys.stderr)
        return 2
    if args.once:
        doc = one_doc(args.statedir)
        if args.json:
            print(json.dumps(doc, sort_keys=True))
        else:
            print("\n".join(render(doc)))
        return 0
    interval = args.interval
    if interval is None:
        # Default to roughly one publisher beat: window = beat * stale_k
        # and stale_k defaults to 3, so window/3 tracks the cadence.
        probe = one_doc(args.statedir)
        windows = [p["window_s"] for p in probe["procs"]]
        interval = max(0.5, (max(windows) / 3.0) if windows else 0.5)
    try:
        return _live(args.statedir, interval)
    except KeyboardInterrupt:
        return 0


if __name__ == "__main__":
    sys.exit(main())
