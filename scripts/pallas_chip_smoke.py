#!/usr/bin/env python
"""On-chip smoke test for the Pallas tier (VERDICT r2: never ship an
untried kernel again).

Runs one tiny pallas_search_span on the default backend, checks the
result against the host oracle, and prints rate for a medium block.
Exit 0 = kernel lowers + bit-exact; nonzero = failure (error printed).
"""
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))


def main() -> int:
    import jax

    from distributed_bitcoinminer_tpu.bitcoin.hash import scan_min
    from distributed_bitcoinminer_tpu.models import NonceSearcher
    from distributed_bitcoinminer_tpu.utils.config import (
        apply_jax_platform_env)

    # Honor JAX_PLATFORMS=cpu for off-chip runs: this image's
    # sitecustomize overrides the env var, and with the tunnel
    # blackholed a bare jax.devices() would hang forever (utils.config).
    apply_jax_platform_env()

    # The baseline legs must measure the DEFAULT kernel config: an
    # inherited DBM_PEEL pin would silently turn the 'vs rolled' delta
    # of the candidate leg below into peel-vs-peel. Restored on exit.
    prior_peel = os.environ.pop("DBM_PEEL", None)

    print(f"platform={jax.devices()[0].platform}", flush=True)
    data = "cmu440"
    # Small batch for the correctness legs: off-chip they run in the
    # Mosaic simulator, where a 2^20-lane dispatch (512 grid steps,
    # 99.6% masked overscan for these tiny ranges) costs minutes for
    # nothing. The on-chip rate leg builds its own wide searcher.
    s = NonceSearcher(data, batch=8192, tier="pallas")

    lo, hi = 2_000_000_000, 2_000_009_999
    t0 = time.time()
    got = s.search(lo, hi)
    print(f"tiny search: {time.time() - t0:.1f}s", flush=True)
    want = scan_min(data, lo, hi)
    if got != want:
        print(f"MISMATCH: {got} != {want}")
        return 1
    print("argmin bit-exact vs oracle", flush=True)

    # Until kernel (r4 SMEM-flag early exit + r5 step-0 zeroing): one hit
    # leg and one miss leg, both vs the oracle. A lowering break in the
    # newest constructs must fail HERE, not three tools later.
    from distributed_bitcoinminer_tpu.bitcoin.hash import scan_until
    target = 1 << 56
    got_u = s.search_until(lo, hi, target)
    want_u = scan_until(data, lo, hi, target)
    if got_u != want_u or s._until_degraded:
        print(f"UNTIL MISMATCH/DEGRADED: {got_u} != {want_u} "
              f"(degraded={s._until_degraded})")
        return 1
    got_m = s.search_until(lo, lo + 999, 1)      # unreachable target
    want_m = scan_until(data, lo, lo + 999, 1)
    if got_m != want_m or s._until_degraded:
        # The miss leg is the first dispatch that runs EVERY grid step's
        # full SHA body; a runtime fault there would silently degrade to
        # the jnp tier and still answer bit-exactly.
        print(f"UNTIL MISS MISMATCH/DEGRADED: {got_m} != {want_m} "
              f"(degraded={s._until_degraded})")
        return 1
    print("until bit-exact vs oracle (hit + miss legs)", flush=True)

    # 2-block tail (long data, 2 device compressions/nonce vs 1) with
    # the r5 digit hoist active — the geometry the rows sweep has not
    # covered on-chip.
    s2 = NonceSearcher("x" * 57, batch=8192, tier="pallas")
    got2 = s2.search(lo, lo + 4095)
    want2 = scan_min("x" * 57, lo, lo + 4095)
    if got2 != want2:
        print(f"2-BLOCK MISMATCH: {got2} != {want2}")
        return 1
    print("2-block tail bit-exact vs oracle", flush=True)

    from distributed_bitcoinminer_tpu.utils.config import CHIP_PLATFORMS
    if jax.devices()[0].platform not in CHIP_PLATFORMS:
        # Off-chip the correctness legs above ran in the Mosaic
        # simulator; a 2^26 rate there takes hours and means nothing.
        print("rate leg skipped off-chip", flush=True)
        return 0
    lo, hi = 2_000_000_000, 2_000_000_000 + (1 << 26) - 1
    s = NonceSearcher(data, batch=1 << 20, tier="pallas")
    warm = s.search(lo, hi)  # warm the big signature
    t0 = time.time()
    timed = s.search(lo, hi)
    dt = time.time() - t0
    # The wide-batch geometry is the one bench and the miner actually
    # run; its masking/overscan handling must be checked here too, not
    # only at the 8192-batch correctness legs above. Oracle: the native
    # scan (2^26 via Python hashlib would take minutes); if the native
    # toolchain is somehow absent, at least pin warm == timed.
    from distributed_bitcoinminer_tpu import native
    if native.available():
        want = native.scan_min_native(data, lo, hi)  # inclusive bounds
        if warm != want or timed != want:
            print(f"WIDE-BATCH MISMATCH: warm={warm} timed={timed} "
                  f"!= {want}")
            return 1
        print("wide-batch (2^20) bit-exact vs native oracle", flush=True)
    elif warm != timed:
        print(f"WIDE-BATCH NONDETERMINISM: {warm} != {timed}")
        return 1
    else:
        print("wide-batch (2^20) warm==timed (native oracle unavailable)",
              flush=True)
    print(f"rate={(hi - lo + 1) / dt / 1e6:.1f}M nonces/s ({dt:.2f}s)",
          flush=True)

    # r5 peeled-compression CANDIDATE (sha256_pallas.peel_enabled):
    # bit-exactness + rate on the same wide geometry, plus a tiny until
    # leg, purely informational — the flip to default-on is decided from
    # this log, and a candidate failure must NOT block the validated
    # default kernel's evidence chain. (If the peel default ever flips
    # to on, the legs above already cover it and this block should be
    # retired or inverted to measure the rolled kernel instead.)
    try:
        os.environ["DBM_PEEL"] = "1"      # dispatch wrappers read per call
        sp = NonceSearcher(data, batch=1 << 20, tier="pallas")
        pwarm = sp.search(lo, hi)
        t0 = time.time()
        ptimed = sp.search(lo, hi)
        pdt = time.time() - t0
        ref = want if native.available() else warm
        if pwarm != ref or ptimed != ref:
            print(f"peel candidate MISMATCH: warm={pwarm} timed={ptimed} "
                  f"!= {ref}")
        else:
            su = NonceSearcher(data, batch=8192, tier="pallas")
            tgt = 1 << 56
            gu = su.search_until(2_000_000_000, 2_000_009_999, tgt)
            wu = scan_until(data, 2_000_000_000, 2_000_009_999, tgt)
            if gu != wu or su._until_degraded:
                print(f"peel candidate UNTIL MISMATCH: {gu} != {wu} "
                      f"(degraded={su._until_degraded})")
            elif not native.available():
                # Without the native oracle, `ref` is only the rolled
                # kernel's warm result — consistency, not correctness. A
                # shared miscompare would sail through, so print a marker
                # chip_chain's bench-peel precondition does NOT accept
                # (ADVICE r5: 'ok' must mean oracle-verified).
                print(f"peel candidate consistent (no oracle): "
                      f"rate={(hi - lo + 1) / pdt / 1e6:.1f}M nonces/s "
                      f"({pdt:.2f}s) vs rolled {(hi - lo + 1) / dt / 1e6:.1f}M",
                      flush=True)
            else:
                print(f"peel candidate ok: "
                      f"rate={(hi - lo + 1) / pdt / 1e6:.1f}M nonces/s "
                      f"({pdt:.2f}s) vs rolled {(hi - lo + 1) / dt / 1e6:.1f}M",
                      flush=True)
    except Exception as exc:  # noqa: BLE001 — candidate only, never gate
        print(f"peel candidate error: {exc!r}"[:400], flush=True)
    finally:
        if prior_peel is None:
            os.environ.pop("DBM_PEEL", None)
        else:
            os.environ["DBM_PEEL"] = prior_peel
    return 0


if __name__ == "__main__":
    rc = main()
    sys.stdout.flush()
    os._exit(rc)
