#!/usr/bin/env python
"""On-chip smoke test for the Pallas tier (VERDICT r2: never ship an
untried kernel again).

Runs one tiny pallas_search_span on the default backend, checks the
result against the host oracle, and prints rate for a medium block.
Exit 0 = kernel lowers + bit-exact; nonzero = failure (error printed).
"""
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))


def main() -> int:
    import jax

    from distributed_bitcoinminer_tpu.bitcoin.hash import scan_min
    from distributed_bitcoinminer_tpu.models import NonceSearcher

    print(f"platform={jax.devices()[0].platform}", flush=True)
    data = "cmu440"
    s = NonceSearcher(data, batch=1 << 20, tier="pallas")

    lo, hi = 2_000_000_000, 2_000_009_999
    t0 = time.time()
    got = s.search(lo, hi)
    print(f"tiny search: {time.time() - t0:.1f}s", flush=True)
    want = scan_min(data, lo, hi)
    if got != want:
        print(f"MISMATCH: {got} != {want}")
        return 1
    print("bit-exact vs oracle", flush=True)

    lo, hi = 2_000_000_000, 2_000_000_000 + (1 << 26) - 1
    s.search(lo, hi)  # warm the big signature
    t0 = time.time()
    s.search(lo, hi)
    dt = time.time() - t0
    print(f"rate={(hi - lo + 1) / dt / 1e6:.1f}M nonces/s ({dt:.2f}s)",
          flush=True)
    return 0


if __name__ == "__main__":
    rc = main()
    sys.stdout.flush()
    os._exit(rc)
