#!/usr/bin/env python
"""dbmlint CLI — the repo's AST invariant gate (ISSUE 7).

Usage:
    python scripts/dbmlint.py                 # check against the baseline
    python scripts/dbmlint.py --list          # print every finding
    python scripts/dbmlint.py --analyzer X    # run one analyzer
    python scripts/dbmlint.py --update-baseline [--force]

Exit codes: 0 clean (new findings: none), 1 new findings, 2 usage/setup.

Pure AST + text: no JAX import, runs in seconds — this is the fast leg
``scripts/tier1.sh`` runs before pytest (``DBM_TIER1_LINT=0`` skips).

Baseline workflow: ``distributed_bitcoinminer_tpu/analysis/baseline.json``
holds the accepted findings by stable key. A finding not in the baseline
FAILS the run (fix it, or suppress it at the site with
``# dbmlint: ok[<analyzer>] <why>``, or — rarely — grow the baseline
with ``--update-baseline --force``). A baseline entry that stops firing
is STALE; ``--update-baseline`` flushes it, so the file shrinks
monotonically over the repo's life.
"""

from __future__ import annotations

import argparse
import os
import sys

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO)

from distributed_bitcoinminer_tpu.analysis import (   # noqa: E402
    compare, load_baseline, run_repo, save_baseline)
from distributed_bitcoinminer_tpu.analysis.core import (   # noqa: E402
    baseline_path)


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    parser.add_argument("--repo", default=_REPO, help="repo root")
    parser.add_argument("--baseline", default=None,
                        help="baseline file (default: "
                             "analysis/baseline.json under --repo)")
    parser.add_argument("--analyzer", default=None,
                        help="run only this analyzer")
    parser.add_argument("--list", action="store_true",
                        help="print every finding (known ones included)")
    parser.add_argument("--update-baseline", action="store_true",
                        help="rewrite the baseline to the current "
                             "finding set (shrink-only without --force)")
    parser.add_argument("--force", action="store_true",
                        help="allow --update-baseline to ADD findings")
    args = parser.parse_args(argv)

    if args.update_baseline and args.analyzer:
        # A partial run sees only one analyzer's findings; rewriting the
        # baseline from it would flush every OTHER analyzer's accepted
        # entries as "stale" and corrupt the shrink-only workflow.
        print("dbmlint: --update-baseline requires a full run; drop "
              "--analyzer", file=sys.stderr)
        return 2

    bpath = args.baseline or baseline_path(args.repo)
    baseline = load_baseline(bpath)
    if args.analyzer:
        # Partial run: other analyzers' baseline entries are invisible
        # to it, not stale.
        baseline = {k: v for k, v in baseline.items()
                    if k.startswith(args.analyzer + ":")}
    findings = run_repo(args.repo, only=args.analyzer)
    new, known, stale = compare(findings, baseline)

    if args.list:
        for f in findings:
            mark = "NEW " if f.key not in baseline else "base"
            print(f"{mark} {f.render()}")

    if args.update_baseline:
        if new and not args.force:
            print(f"dbmlint: refusing to GROW the baseline by "
                  f"{len(new)} finding(s) without --force "
                  f"(fix or suppress them instead):", file=sys.stderr)
            for f in new:
                print("  " + f.render(), file=sys.stderr)
            return 1
        save_baseline(bpath, findings)
        print(f"dbmlint: baseline rewritten: {len(findings)} entr"
              f"{'y' if len(findings) == 1 else 'ies'} "
              f"({len(stale)} stale flushed, {len(new)} added)")
        return 0

    if new:
        print(f"dbmlint: {len(new)} NEW finding(s) "
              f"(not in {os.path.relpath(bpath, args.repo)}):",
              file=sys.stderr)
        for f in new:
            print("  " + f.render(), file=sys.stderr)
        return 1
    if stale:
        print(f"dbmlint: clean; {len(stale)} stale baseline entr"
              f"{'y' if len(stale) == 1 else 'ies'} no longer fire — "
              f"flush with --update-baseline:")
        for k in stale:
            print("  " + k)
    n = len(findings)
    print(f"dbmlint: clean ({n} known finding(s) baselined, "
          f"0 new)" if n else "dbmlint: clean (no findings)")
    return 0


if __name__ == "__main__":
    sys.exit(main())
