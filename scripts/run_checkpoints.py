#!/usr/bin/env python
"""Per-process test driver: every test file in its own interpreter.

The grading harness the reference was graded under runs each scenario as
its own OS process (ref: p1/sh/run_test_checkpoint2.sh — one `go test
-race -run TestX` per line), so a wedged event loop or a poisoned
process-global (fault knobs, sniffer counters) in one scenario can never
cascade into the next. All ~180 tests here normally share one pytest
interpreter; this driver restores the harness's isolation at file
granularity (VERDICT r3 missing #2): one `pytest <file>` subprocess per
test file, a summary table, exit 0 iff every file passes.

Usage: python scripts/run_checkpoints.py [test_file ...]
       (no args = every tests/test_*.py)
"""

from __future__ import annotations

import glob
import os
import re
import subprocess
import sys
import time

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
_PER_FILE_TIMEOUT = 1200  # seconds; the slowest file (scale) needs ~300


def run_file(path: str) -> tuple[str, int, int, float]:
    """Run one test file in a fresh interpreter.

    Returns (status, passed, failed, seconds); status is 'ok', 'FAIL',
    or 'TIMEOUT'.
    """
    t0 = time.monotonic()
    try:
        proc = subprocess.run(
            [sys.executable, "-m", "pytest", path, "-q", "--tb=line"],
            cwd=_REPO, env={**os.environ, "PYTHONPATH": _REPO},
            capture_output=True, text=True, timeout=_PER_FILE_TIMEOUT)
    except subprocess.TimeoutExpired:
        return "TIMEOUT", 0, 0, time.monotonic() - t0
    elapsed = time.monotonic() - t0
    tail = proc.stdout.strip().splitlines()[-1] if proc.stdout else ""
    passed = sum(int(n) for n in re.findall(r"(\d+) passed", tail))
    failed = sum(int(n) for n in re.findall(r"(\d+) (?:failed|error)", tail))
    status = "ok" if proc.returncode == 0 else "FAIL"
    if status == "FAIL" and proc.stdout:
        sys.stdout.write(proc.stdout)
    return status, passed, failed, elapsed


def main(argv=None) -> int:
    argv = sys.argv[1:] if argv is None else argv
    files = argv or sorted(
        glob.glob(os.path.join(_REPO, "tests", "test_*.py")))
    total_pass = total_fail = bad_files = 0
    print(f"{'file':<34} {'status':<8} {'pass':>5} {'fail':>5} {'time':>8}")
    for path in files:
        name = os.path.basename(path)
        status, passed, failed, elapsed = run_file(path)
        total_pass += passed
        total_fail += failed
        if status != "ok":
            bad_files += 1
        print(f"{name:<34} {status:<8} {passed:>5} {failed:>5} "
              f"{elapsed:>7.1f}s", flush=True)
    print(f"\n{len(files)} files, {total_pass} passed, {total_fail} failed, "
          f"{bad_files} bad files")
    return 0 if bad_files == 0 else 1


if __name__ == "__main__":
    rc = main()
    sys.stdout.flush()
    # Interpreter-shutdown finalizers can hang under this image's axon
    # plugin (see utils/config.py notes); hard-exit like bench.py.
    os._exit(rc)
