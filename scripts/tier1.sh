#!/usr/bin/env bash
# Tier-1 verify gate — the ONE command builders, CI, and the driver run.
#
# The pytest command is byte-identical to the ROADMAP.md "Tier-1 verify"
# line (keep them in sync): CPU-pinned pytest over tests/, not-slow only,
# collection errors surfaced but non-fatal, 870s wall budget, and a
# DOTS_PASSED count (passing-test dots in the -q progress lines) printed
# at the end so runs that time out mid-suite still yield a comparable
# score. One deliberate addition over the ROADMAP line (ISSUE 3): the
# suite runs with DBM_METRICS_INTERVAL_S set, so the periodic metrics
# emitter is exercised under the full suite's load (every scheduler/miner
# construction starts it) instead of only in its own unit tests.
# Override by exporting DBM_METRICS_INTERVAL_S yourself (0 disables).
#
# Usage: scripts/tier1.sh            (from anywhere; cd's to the repo root)
# Exit code is pytest's (or timeout's 124/143 on budget exhaustion).

set -o pipefail
cd "$(dirname "$0")/.." || exit 2
export DBM_METRICS_INTERVAL_S="${DBM_METRICS_INTERVAL_S:-2}"

rm -f /tmp/_t1.log
timeout -k 10 870 env JAX_PLATFORMS=cpu \
    python -m pytest tests/ -q -m 'not slow' \
    --continue-on-collection-errors \
    -p no:cacheprovider -p no:xdist -p no:randomly 2>&1 | tee /tmp/_t1.log
rc=${PIPESTATUS[0]}
echo DOTS_PASSED=$(grep -aE '^[.FEsx]+( *\[ *[0-9]+%\])?$' /tmp/_t1.log | tr -cd . | wc -c)
exit $rc
