#!/usr/bin/env bash
# Tier-1 verify gate — the ONE command builders, CI, and the driver run.
#
# The pytest command is byte-identical to the ROADMAP.md "Tier-1 verify"
# line (keep them in sync): CPU-pinned pytest over tests/, not-slow only,
# collection errors surfaced but non-fatal, wall-budgeted, and a
# DOTS_PASSED count (passing-test dots in the -q progress lines) printed
# at the end so runs that time out mid-suite still yield a comparable
# score. The wall budget scales with the box (ISSUE 18): the original
# 870s was calibrated on a 2-core runner, and a 1-core box needs roughly
# double the wall for the same suite — so the default derives from
# `nproc` (>=2 cores: 870s main / 480s matrix; 1 core: 1740s / 960s) and
# DBM_TIER1_BUDGET_S overrides the main-leg budget explicitly (the
# matrix leg stays proportional at ~55%). The ROADMAP line quotes the
# 1740s figure — a cap, safe on any box. One deliberate addition over the ROADMAP line (ISSUE 3): the
# suite runs with DBM_METRICS_INTERVAL_S set, so the periodic metrics
# emitter is exercised under the full suite's load (every scheduler/miner
# construction starts it) instead of only in its own unit tests.
# Override by exporting DBM_METRICS_INTERVAL_S yourself (0 disables).
# A second deliberate addition (ISSUE 4): after a green main leg, a
# knob-off matrix leg re-runs the recovery/chaos/parity modules with
# DBM_PIPELINE=0 DBM_STRIPE=0 (see below; DBM_TIER1_MATRIX=0 skips).
# A third (ISSUE 7): a dbmlint leg runs BEFORE pytest — pure AST, no
# JAX import, seconds — and its failure fails the gate without eating
# the pytest budget (tests still run so DOTS_PASSED stays comparable).
# DBM_TIER1_LINT=0 skips it.
#
# Usage: scripts/tier1.sh            (from anywhere; cd's to the repo root)
# Exit code is pytest's (or timeout's 124/143 on budget exhaustion).

set -o pipefail
cd "$(dirname "$0")/.." || exit 2
export DBM_METRICS_INTERVAL_S="${DBM_METRICS_INTERVAL_S:-2}"

# Wall budgets, nproc-derived (ISSUE 18 satellite): the 870s main-leg
# budget was set on a 2-core box; a 1-core box runs the same suite in
# roughly twice the wall, so it timed out mid-suite and under-counted
# DOTS_PASSED. DBM_TIER1_BUDGET_S pins the main budget explicitly; the
# matrix leg scales proportionally (~55% of main, the historical
# 480/870 ratio).
cores=$(nproc 2>/dev/null || echo 2)
if [ "${cores:-2}" -ge 2 ]; then
    budget_default=870
else
    budget_default=1740
fi
budget="${DBM_TIER1_BUDGET_S:-$budget_default}"
matrix_budget=$(awk -v b="$budget" 'BEGIN{printf "%d", (b*55)/100}')

# dbmlint leg (ISSUE 7): the repo's AST invariant gate
# (scripts/dbmlint.py vs analysis/baseline.json). New findings fail;
# the run costs seconds because nothing imports JAX.
lint_rc=0
if [ "${DBM_TIER1_LINT:-1}" != "0" ]; then
    timeout -k 5 120 python scripts/dbmlint.py
    lint_rc=$?
    echo "DBMLINT_RC=$lint_rc"
fi

# dbmcheck leg (ISSUE 8): deterministic interleaving exploration of the
# control plane (scripts/dbmcheck.py) — a fixed seed budget of random
# walks plus a bounded DFS pass over the scenario catalog, every
# schedule invariant-checked, every failure printed with a replayable
# (and shrunk) seed spec. Runs BEFORE pytest like the lint leg: no JAX
# import, the whole budget is wall-bounded (DBM_CHECK_BUDGET_S, default
# 75s), and its rc folds into the gate without eating the pytest
# budget. DBM_CHECK=0 skips; DBM_CHECK_SEEDS / DBM_CHECK_DFS /
# DBM_CHECK_SCENARIOS tune the sweep.
# The leg also enforces an exploration FLOOR: a starved box whose wall
# budget expired after a handful of schedules would otherwise pass
# green having checked nothing (the "checker went blind" failure mode).
# DBM_CHECK_MIN_DISTINCT (default 500, 0 disables — lower it alongside
# DBM_CHECK_SEEDS on deliberately small runs) bounds it.
check_rc=0
if [ "${DBM_CHECK:-1}" != "0" ]; then
    rm -f /tmp/_t1_check.log
    # Kill deadline derives from the documented budget knob (it must
    # not silently cap it) + headroom for the post-exploration shrink
    # passes a violation triggers (up to 400 re-executions each).
    check_kill=$(awk -v b="${DBM_CHECK_BUDGET_S:-75}" \
        'BEGIN{printf "%d", (b+0)+90}')
    timeout -k 5 "$check_kill" python scripts/dbmcheck.py 2>&1 \
        | tee /tmp/_t1_check.log
    check_rc=${PIPESTATUS[0]}
    distinct=$(grep -a '^DBMCHECK_DISTINCT=' /tmp/_t1_check.log | tail -1 | cut -d= -f2)
    min_distinct="${DBM_CHECK_MIN_DISTINCT:-500}"
    if [ "$check_rc" -eq 0 ] && [ "$min_distinct" != "0" ] && \
       [ "${distinct:-0}" -lt "$min_distinct" ]; then
        echo "DBMCHECK_FLOOR: only ${distinct:-0} distinct schedules" \
             "explored (< $min_distinct) — treating as failure"
        check_rc=3
    fi
    echo "DBMCHECK_LEG_RC=$check_rc"
fi

# Mini-load leg (ISSUE 11): a bounded ~500-tenant storm through the
# split scheduler on the socket-free detnet transport with instant
# miners — no JAX import, seconds of wall. Gates on completion (every
# non-shed request answered), a generous reply-p99 ceiling (the box may
# be loaded; the ceiling catches a MELT, not jitter), and bounded
# metric-series growth (per-tenant labels must collapse under the
# cardinality bound, not explode). DBM_TIER1_LOAD=0 skips.
load_rc=0
if [ "${DBM_TIER1_LOAD:-1}" != "0" ]; then
    timeout -k 5 180 python scripts/loadharness.py --tenants 500 \
        --replicas 2 --assert-p99 60 --assert-series 512
    load_rc=$?
    echo "LOAD_LEG_RC=$load_rc"
fi

# Adapt leg (ISSUE 13): the self-tuning control plane's stability +
# payoff gate. (a) dbmcheck's adaptive_control scenario alone at a
# >=500 distinct-schedule floor — the controller-specific invariants
# (hard clamps, bounded oscillation amplitude) on the virtual clock
# against drifting miner rates; (b) a mini mice-stampede workload with
# the controllers ON, gated on completion fraction and reply p99 (the
# adaptive plane must keep the queue near the service floor — the
# ceiling catches a runaway controller, not box jitter). No JAX
# import in either half. DBM_TIER1_ADAPT=0 skips.
adapt_rc=0
if [ "${DBM_TIER1_ADAPT:-1}" != "0" ]; then
    rm -f /tmp/_t1_adapt.log
    timeout -k 5 150 python scripts/dbmcheck.py \
        --scenario adaptive_control --seeds 700 2>&1 \
        | tee /tmp/_t1_adapt.log
    adapt_rc=${PIPESTATUS[0]}
    adistinct=$(grep -a '^DBMCHECK_DISTINCT=' /tmp/_t1_adapt.log | tail -1 | cut -d= -f2)
    if [ "$adapt_rc" -eq 0 ] && [ "${adistinct:-0}" -lt 500 ]; then
        echo "ADAPT_FLOOR: only ${adistinct:-0} distinct schedules" \
             "explored (< 500) — treating as failure"
        adapt_rc=3
    fi
    if [ "$adapt_rc" -eq 0 ]; then
        timeout -k 5 120 python scripts/loadharness.py \
            --workload mice_stampede --adapt 1 \
            --assert-complete 0.5 --assert-p99 2.0
        adapt_rc=$?
    fi
    echo "ADAPT_LEG_RC=$adapt_rc"
fi

# Mesh smoke leg (ISSUE 14): an 8-virtual-device CPU mesh registers as
# ONE miner (measured rate-hint JOIN) against an embedded scheduler
# over real localhost LSP; one elephant must come back oracle-exact
# with exactly ONE device launch and ONE host fetch per whole-mesh
# span (the carry-chained one-pair-per-span contract).
# DBM_TIER1_MESH=0 skips.
mesh_rc=0
if [ "${DBM_TIER1_MESH:-1}" != "0" ]; then
    timeout -k 5 420 python scripts/meshsmoke.py
    mesh_rc=$?
    echo "MESH_LEG_RC=$mesh_rc"
fi

# Replay leg (ISSUE 15): the capture→replay round trip as a gate.
# (a) capture a mini detnet storm (the capture plane armed on the
# mini-load harness shape); (b) replay the capture under the stated
# fidelity bounds (--assert-fidelity: admitted/s ratio, p99 ratio,
# shed delta, request-count equality); (c) run the dbmcheck
# replayed_storm scenario over the FRESH capture — interleaving
# exploration on this session's own measured traffic — with the same
# >=500 distinct-schedule floor as the other dbmcheck legs. No JAX
# import anywhere. DBM_TIER1_REPLAY=0 skips.
replay_rc=0
if [ "${DBM_TIER1_REPLAY:-1}" != "0" ]; then
    rm -f /tmp/_t1_cap.jsonl /tmp/_t1_cap.jsonl.1 /tmp/_t1_replay.log
    timeout -k 5 120 python scripts/loadharness.py --tenants 300 \
        --capture-to /tmp/_t1_cap.jsonl
    replay_rc=$?
    if [ "$replay_rc" -eq 0 ]; then
        timeout -k 5 120 python scripts/loadharness.py \
            --replay /tmp/_t1_cap.jsonl --assert-fidelity
        replay_rc=$?
    fi
    if [ "$replay_rc" -eq 0 ]; then
        DBM_CHECK_CAPTURE=/tmp/_t1_cap.jsonl timeout -k 5 150 \
            python scripts/dbmcheck.py --scenario replayed_storm \
            --seeds 700 2>&1 | tee /tmp/_t1_replay.log
        replay_rc=${PIPESTATUS[0]}
        rdistinct=$(grep -a '^DBMCHECK_DISTINCT=' /tmp/_t1_replay.log | tail -1 | cut -d= -f2)
        if [ "$replay_rc" -eq 0 ] && [ "${rdistinct:-0}" -lt 500 ]; then
            echo "REPLAY_FLOOR: only ${rdistinct:-0} distinct schedules" \
                 "explored (< 500) — treating as failure"
            replay_rc=3
        fi
    fi
    echo "REPLAY_LEG_RC=$replay_rc"
fi

# Byzantine leg (ISSUE 16): dbmcheck's byzantine_miner scenario family
# alone — wrong-hash fabricators, colluding duplicates, sentinel
# without-scan and selectively-correct liars under the exactly-once
# oracle-exact invariant pack — with the same >=500 distinct-schedule
# floor as the other dbmcheck legs (a verification tier that explored
# nothing proves nothing). No JAX import. DBM_TIER1_BYZ=0 skips.
byz_rc=0
if [ "${DBM_TIER1_BYZ:-1}" != "0" ]; then
    rm -f /tmp/_t1_byz.log
    timeout -k 5 150 python scripts/dbmcheck.py \
        --scenario byzantine_wrong_hash,byzantine_collude,byzantine_sentinel,byzantine_selective \
        --seeds 200 2>&1 | tee /tmp/_t1_byz.log
    byz_rc=${PIPESTATUS[0]}
    bdistinct=$(grep -a '^DBMCHECK_DISTINCT=' /tmp/_t1_byz.log | tail -1 | cut -d= -f2)
    if [ "$byz_rc" -eq 0 ] && [ "${bdistinct:-0}" -lt 500 ]; then
        echo "BYZ_FLOOR: only ${bdistinct:-0} distinct schedules" \
             "explored (< 500) — treating as failure"
        byz_rc=3
    fi
    echo "BYZ_LEG_RC=$byz_rc"
fi

# Federation leg (ISSUE 20): dbmcheck's federation scenario alone — a
# parent scheduler with two whole child clusters JOINed through
# GatewayMiners (pool-summed rate hints over the Rate extension, grant
# translation, in-order upward forwarding, hint refresh, mid-schedule
# child-cluster failover) under the full exactly-once oracle-exact
# invariant pack, with the same >=500 distinct-schedule floor as the
# other dbmcheck legs. No JAX import. DBM_TIER1_FED=0 skips.
fed_rc=0
if [ "${DBM_TIER1_FED:-1}" != "0" ]; then
    rm -f /tmp/_t1_fed.log
    timeout -k 5 150 python scripts/dbmcheck.py \
        --scenario federation --seeds 700 2>&1 | tee /tmp/_t1_fed.log
    fed_rc=${PIPESTATUS[0]}
    fdistinct=$(grep -a '^DBMCHECK_DISTINCT=' /tmp/_t1_fed.log | tail -1 | cut -d= -f2)
    if [ "$fed_rc" -eq 0 ] && [ "${fdistinct:-0}" -lt 500 ]; then
        echo "FED_FLOOR: only ${fdistinct:-0} distinct schedules" \
             "explored (< 500) — treating as failure"
        fed_rc=3
    fi
    echo "FED_LEG_RC=$fed_rc"
fi

# Multi-process smoke leg (ISSUE 12): the REAL process topology on
# localhost — router + 2 replica processes on their own LSP sockets +
# 1 miner agent — with a kill -9 of the replica owning an in-flight
# request; the reply must arrive exactly-once and oracle-exact with
# failover driven solely by missed health beats (no test-hook kill
# path exists in the topology). Host-searcher compute, no JAX import.
# DBM_TIER1_PROCS=0 skips.
procs_rc=0
if [ "${DBM_TIER1_PROCS:-1}" != "0" ]; then
    timeout -k 5 180 python scripts/procsmoke.py
    procs_rc=$?
    echo "PROCS_LEG_RC=$procs_rc"
fi

# Transport-regression leg (ISSUE 17): the echo-storm datapath probe
# (bench.py --transport-only — sockets only, no JAX import) diffed
# against the checked-in floor artifact with benchdiff. Gates two
# leaves: fast-datapath msgs/s (a collapse of the batched/zero-alloc
# path) and the fast-vs-stock speedup (near 1.0 = the DBM_MMSG /
# DBM_WIRE_FAST knobs silently stopped mattering). Floors sit far
# under the measured medians so box noise passes; a real datapath
# regression does not. DBM_TIER1_TRANSPORT=0 skips.
transport_rc=0
if [ "${DBM_TIER1_TRANSPORT:-1}" != "0" ]; then
    rm -f /tmp/_t1_transport.json
    timeout -k 5 180 python bench.py --transport-only \
        > /tmp/_t1_transport.json
    transport_rc=$?
    if [ "$transport_rc" -eq 0 ]; then
        timeout -k 5 60 python scripts/benchdiff.py \
            scripts/transport_floor.json /tmp/_t1_transport.json \
            --threshold 0.3
        transport_rc=$?
    fi
    echo "TRANSPORT_LEG_RC=$transport_rc"
fi

rm -f /tmp/_t1.log
timeout -k 10 "$budget" env JAX_PLATFORMS=cpu \
    python -m pytest tests/ -q -m 'not slow' \
    --continue-on-collection-errors \
    -p no:cacheprovider -p no:xdist -p no:randomly 2>&1 | tee /tmp/_t1.log
rc=${PIPESTATUS[0]}
echo DOTS_PASSED=$(grep -aE '^[.FEsx]+( *\[ *[0-9]+%\])?$' /tmp/_t1.log | tr -cd . | wc -c)

# Knob-off matrix leg (ISSUE 4 + ISSUE 5 + ISSUE 9 + ISSUE 10): the
# dispatch pipeline, request striping, the fair-share QoS plane,
# cross-request coalescing, and the tracing plane default ON, so the
# full run above exercises the overlapped/fair-share/batched/traced
# path — re-run the recovery/chaos/parity-sensitive modules (plus the
# QoS suite, the batch suite, and the trace suite, whose
# FIFO/stock-dispatch/stock-bytes parity pins are exactly what this
# leg exists for) with DBM_PIPELINE=0 DBM_STRIPE=0 DBM_QOS=0
# DBM_COALESCE=0 DBM_TRACE=0 so the stock serial loop + reference even
# split + FIFO dispatch order + one-chunk-one-dispatch + span-less
# stock wire bytes (the Go-parity shape) stays covered in CI too. The
# leg also runs with DBM_SANITIZE=1 (ISSUE 7): the chaos and QoS
# suites under it exercise real wedges, kills, and concurrent
# dispatch, so the sanitizer's loop-stall watchdog and
# thread-ownership assertions sweep the paths most likely to regress —
# violations warn and count, never fail a test, so this costs nothing
# when clean. Skipped when the main leg already blew the budget.
# DBM_TIER1_MATRIX=0 opts out.
if [ "$rc" -eq 0 ] && [ "${DBM_TIER1_MATRIX:-1}" != "0" ]; then
    # ISSUE 11 additions to the knob-off matrix: DBM_RECV_BATCH=1
    # (stock one-message-per-await recv), DBM_TIMER_WHEEL=0 (per-conn
    # epoch tasks), DBM_TRACE_SAMPLE=1.0 (every request allocates its
    # trace — stock), DBM_REPLICAS=1 (single-scheduler topology), and
    # the plane-split suite joins the module list. ISSUE 12 addition:
    # DBM_QOS_LAZY=0 pins the STOCK DRR candidate walk (the lazy
    # ring walk is default-on everywhere else in the gate). ISSUE 13
    # addition: DBM_ADAPT=0 pins the static-knob control plane (no
    # controller objects anywhere — the bit-for-bit stock contract the
    # adapt suite's parity tests assert), with test_adapt.py in the
    # module list.
    # ISSUE 14 additions: DBM_MESH=0 pins the round-3 local-device
    # sharding model (per-sub partials — the stock multi-device plane)
    # and DBM_ADAPT=0 now pins the flipped default (the plane is ON in
    # the main leg since the ISSUE 13 soak ran clean).
    # ISSUE 15 addition: DBM_CAPTURE=0 pins the no-capture-plane shape
    # (the default, pinned EXPLICITLY so an env leak cannot arm it)
    # with test_capture.py — whose parity pin asserts byte-identical
    # replies capture-on vs capture-off — in the module list.
    # ISSUE 16 addition: DBM_VERIFY=0 pins the believe-every-Result
    # stock merge (no recompute, no trust bookkeeping, no audit state)
    # with test_verify.py — whose parity pin asserts byte-identical
    # write streams verify-off vs claim-checks-on — in the module list.
    # ISSUE 17 additions: DBM_MMSG=0 pins the stock asyncio datagram
    # transport (one syscall per packet) and DBM_WIRE_FAST=0 pins the
    # stock json/base64 codec (Message.to_json/from_json) — together
    # the bit-for-bit pre-ISSUE-17 wire path — with test_wire.py and
    # test_transport_fast.py (whose parity pins assert byte-identical
    # frames fast-vs-stock) in the module list.
    # ISSUE 18 addition: DBM_ROLLUP=0 pins the no-observability-plane
    # shape (no publisher objects, no metrics_* blobs, no identity
    # stamps — the bit-for-bit stock contract test_rollup.py's
    # knob-off tests assert) with test_rollup.py in the module list.
    # ISSUE 19 addition: DBM_DEVLOOP=0 pins the stock pow2 sub-dispatch
    # chain (one launch + one fetched triple per sub — the bit-for-bit
    # pre-devloop dispatch shape test_devloop.py's parity pins assert)
    # with test_devloop.py in the module list.
    # ISSUE 20 additions: DBM_GATEWAY=0 pins the flat single-tier
    # topology (a repeat JOIN registers a fresh roster entry instead of
    # refreshing in place — the stock shape test_federation.py's
    # knob-off tests assert) and DBM_AUDIT_P=0 pins the audit-free
    # verify tier (the pre-flip env default), with
    # tests/test_federation.py in the module list.
    timeout -k 10 "$matrix_budget" env JAX_PLATFORMS=cpu \
        DBM_PIPELINE=0 DBM_STRIPE=0 \
        DBM_QOS=0 DBM_COALESCE=0 DBM_TRACE=0 DBM_SANITIZE=1 \
        DBM_RECV_BATCH=1 DBM_TIMER_WHEEL=0 DBM_TRACE_SAMPLE=1.0 \
        DBM_REPLICAS=1 DBM_QOS_LAZY=0 DBM_ADAPT=0 DBM_MESH=0 \
        DBM_CAPTURE=0 DBM_VERIFY=0 DBM_MMSG=0 DBM_WIRE_FAST=0 \
        DBM_ROLLUP=0 DBM_DEVLOOP=0 DBM_GATEWAY=0 DBM_AUDIT_P=0 \
        python -m pytest -q -m 'not slow' \
        tests/test_scheduler_recovery.py tests/test_chaos.py \
        tests/test_conformance.py tests/test_go_replay.py \
        tests/test_apps.py tests/test_qos.py tests/test_batch.py \
        tests/test_trace.py tests/test_plane_split.py \
        tests/test_adapt.py tests/test_capture.py tests/test_verify.py \
        tests/test_wire.py tests/test_transport_fast.py \
        tests/test_rollup.py tests/test_devloop.py \
        tests/test_federation.py \
        -p no:cacheprovider -p no:xdist -p no:randomly 2>&1 \
        | tee /tmp/_t1_matrix.log
    mrc=${PIPESTATUS[0]}
    echo "MATRIX_KNOBS_OFF_RC=$mrc"
    [ "$mrc" -ne 0 ] && rc=$mrc
fi
[ "$lint_rc" -ne 0 ] && [ "$rc" -eq 0 ] && rc=$lint_rc
[ "$check_rc" -ne 0 ] && [ "$rc" -eq 0 ] && rc=$check_rc
[ "$load_rc" -ne 0 ] && [ "$rc" -eq 0 ] && rc=$load_rc
[ "$adapt_rc" -ne 0 ] && [ "$rc" -eq 0 ] && rc=$adapt_rc
[ "$replay_rc" -ne 0 ] && [ "$rc" -eq 0 ] && rc=$replay_rc
[ "$mesh_rc" -ne 0 ] && [ "$rc" -eq 0 ] && rc=$mesh_rc
[ "$byz_rc" -ne 0 ] && [ "$rc" -eq 0 ] && rc=$byz_rc
[ "$fed_rc" -ne 0 ] && [ "$rc" -eq 0 ] && rc=$fed_rc
[ "$procs_rc" -ne 0 ] && [ "$rc" -eq 0 ] && rc=$procs_rc
[ "$transport_rc" -ne 0 ] && [ "$rc" -eq 0 ] && rc=$transport_rc
exit $rc
