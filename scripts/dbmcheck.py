#!/usr/bin/env python
"""dbmcheck CLI — deterministic interleaving exploration of the control
plane (ISSUE 8).

Usage:
    python scripts/dbmcheck.py                    # explore all scenarios
    python scripts/dbmcheck.py --scenario qos_shed --seeds 500
    python scripts/dbmcheck.py --replay 'lease_reissue:rw:42'
    python scripts/dbmcheck.py --replay 'qos_shed:tr:7:0.2.1'
    python scripts/dbmcheck.py --fixtures         # prove the checker bites
    python scripts/dbmcheck.py --list

Exit codes: 0 every explored schedule held every invariant, 1 at least
one violation (each printed with a DBMCHECK_REPRO= seed spec that
replays its schedule bit-for-bit; failing random walks are SHRUNK to a
minimal choice trace first), 2 usage.

Environment defaults (all routed through ``utils/_env``; see the knob
tables in README.md / utils/config.py):

- ``DBM_CHECK_SEEDS``     random-walk seeds per scenario (default 200)
- ``DBM_CHECK_BUDGET_S``  wall budget for the whole run (default 75)
- ``DBM_CHECK_DFS``       bounded-DFS schedules per scenario (default
                          64; 0 disables the DFS pass)
- ``DBM_CHECK_SCENARIOS`` comma-separated scenario subset (default: the
                          full real-scenario catalog)

The process pins ``DBM_METRICS_INTERVAL_S=0`` (no emitter thread racing
the virtual clock) and defaults ``DBM_SANITIZE=1`` so the ownership /
off-loop counters are armed as schedule invariants.
"""

from __future__ import annotations

import argparse
import os
import sys

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO)

# Environment discipline BEFORE the control plane is imported: the
# metrics emitter thread would tick on the patched virtual clock, and
# the sanitizer plane should be armed for every scenario scheduler.
os.environ["DBM_METRICS_INTERVAL_S"] = "0"
os.environ.setdefault("DBM_SANITIZE", "1")

from distributed_bitcoinminer_tpu.utils._env import (   # noqa: E402
    float_env, int_env, str_env)
from distributed_bitcoinminer_tpu.analysis import schedcheck  # noqa: E402


def _parse_args(argv):
    parser = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    parser.add_argument("--scenario", default=None,
                        help="comma-separated scenario subset")
    parser.add_argument("--seeds", type=int,
                        default=int_env("DBM_CHECK_SEEDS", 200),
                        help="random-walk seeds per scenario")
    parser.add_argument("--seed0", type=int, default=0,
                        help="first seed (seed space offset)")
    parser.add_argument("--budget-s", type=float,
                        default=float_env("DBM_CHECK_BUDGET_S", 75.0),
                        help="wall budget for the whole exploration")
    parser.add_argument("--dfs", type=int,
                        default=int_env("DBM_CHECK_DFS", 64),
                        help="bounded-DFS schedules per scenario (0=off)")
    parser.add_argument("--dfs-depth", type=int, default=6,
                        help="choice points the DFS branches over")
    parser.add_argument("--replay", default=None, metavar="SPEC",
                        help="re-execute one seed spec and report")
    parser.add_argument("--fixtures", action="store_true",
                        help="explore the known-bad fixtures instead "
                             "(violations EXPECTED; rc reflects them)")
    parser.add_argument("--list", action="store_true",
                        help="list scenarios and exit")
    return parser.parse_args(argv)


def _report_failure(result, shrunk=None) -> None:
    print(f"\nVIOLATION in {result.scenario} "
          f"(seed {result.seed}, {len(result.steps)} steps):")
    for v in result.violations:
        print(f"  - {v}")
    print(f"  DBMCHECK_REPRO={schedcheck.format_spec(result)}")
    if shrunk is not None:
        print(f"  shrunk to {len([c for c in shrunk.choices if c])} "
              f"non-default choices over {len(shrunk.trace)} choice "
              f"points:")
        print(f"  DBMCHECK_REPRO={schedcheck.format_spec(shrunk, shrunk=True)}")


def main(argv=None) -> int:
    args = _parse_args(argv)
    if args.list:
        for name in schedcheck.SCENARIOS:
            print(f"{name:24s} (scenario)")
        for name in schedcheck.FIXTURES:
            print(f"{name:24s} (known-bad fixture)")
        return 0

    if args.replay:
        result = schedcheck.replay(args.replay)
        print(f"replayed {args.replay}: status={result.status} "
              f"steps={len(result.steps)} "
              f"choice_points={len(result.trace)}")
        if result.failed:
            _report_failure(result)
            return 1
        print("all invariants held")
        return 0

    if args.scenario:
        names = [n.strip() for n in args.scenario.split(",") if n.strip()]
    elif args.fixtures:
        names = list(schedcheck.FIXTURES)
    else:
        env_names = str_env("DBM_CHECK_SCENARIOS", "")
        names = ([n.strip() for n in env_names.split(",") if n.strip()]
                 if env_names else list(schedcheck.SCENARIOS))
    for n in names:
        if n not in schedcheck.ALL:
            print(f"unknown scenario {n!r}; known: "
                  f"{sorted(schedcheck.ALL)}", file=sys.stderr)
            return 2

    stats = schedcheck.explore_scenarios(
        names, seeds=args.seeds, seed0=args.seed0,
        budget_s=args.budget_s, dfs_limit=args.dfs,
        dfs_depth=args.dfs_depth)

    total_explored = total_distinct = 0
    rc = 0
    for name, st in stats.items():
        s = st.summary()
        total_explored += s["explored"]
        total_distinct += s["distinct"]
        print(f"{name:24s} explored={s['explored']:5d} "
              f"distinct={s['distinct']:5d} "
              f"violations={s['violations']:3d} "
              f"elapsed={s['elapsed_s']:6.2f}s")
        for failure in st.failures:
            rc = 1
            shrunk = schedcheck.shrink(failure)
            _report_failure(failure, shrunk)
    print(f"DBMCHECK_EXPLORED={total_explored}")
    print(f"DBMCHECK_DISTINCT={total_distinct}")
    print(f"DBMCHECK_RC={rc}")
    return rc


if __name__ == "__main__":
    sys.exit(main())
