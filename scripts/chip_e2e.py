#!/usr/bin/env python
"""Full-stack end-to-end on the real chip: scheduler + TPU miner + client.

The flagship demo as one command — three OS processes over wire-compatible
LSP/UDP, the miner on the auto (pallas-on-chip) tier, the printed Result
cross-checked bit-for-bit against the native host oracle; then a second
client request carrying a difficulty target, checked against the
first-qualifying-nonce oracle (the miner runs the in-kernel early exit).
This is the run that caught round 3's answer-with-sentinel miner bug (a
failed device backend init produced a legitimate-looking (MAX_U64, 0)
Result), so keep running it whenever the miner's device path changes.

Usage: python scripts/chip_e2e.py [max_nonce]   (default 2^26 - 1)
Exit 0 = Result matches oracle.
"""

from __future__ import annotations

import os
import subprocess
import sys
import time

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
PORT = 18485


def main() -> int:
    max_nonce = int(sys.argv[1]) if len(sys.argv) > 1 else (1 << 26) - 1
    data = "chip-e2e"
    env = {**os.environ, "PYTHONPATH": _REPO}

    # Fast-fail on a wedged tunnel (shared probe, same app resolution
    # order): a dead axon endpoint otherwise shows up as a confusing
    # 5-minute client timeout — and a CPU-resolved fallback would "pass"
    # without validating the chip path this script exists for.
    sys.path.insert(0, _REPO)
    from distributed_bitcoinminer_tpu.utils._env import float_env
    from distributed_bitcoinminer_tpu.utils.config import (CHIP_PLATFORMS,
                                                           probe_backend)
    deadline = float_env("DBM_BENCH_INIT_TIMEOUT", 300.0)
    probe = probe_backend(deadline, _REPO)
    if "error" in probe:
        print(f"chip unreachable: {probe['error']}")
        return 2
    if probe["platform"] not in CHIP_PLATFORMS:
        print(f"chip unreachable: backend resolved to "
              f"{probe['platform']!r}, not a TPU — refusing to run a "
              "false chip e2e")
        return 2

    procs = []

    def spawn(*args):
        p = subprocess.Popen([sys.executable, "-m", *args], env=env,
                             cwd=_REPO, stdout=subprocess.DEVNULL,
                             stderr=subprocess.DEVNULL)
        procs.append(p)
        return p

    try:
        spawn("distributed_bitcoinminer_tpu.apps.server", str(PORT))
        time.sleep(1.5)
        spawn("distributed_bitcoinminer_tpu.apps.miner", f"localhost:{PORT}")
        time.sleep(20)  # device backend init + first-compile headroom
        t0 = time.time()
        out = subprocess.run(
            [sys.executable, "-m", "distributed_bitcoinminer_tpu.apps.client",
             f"localhost:{PORT}", data, str(max_nonce)],
            env=env, cwd=_REPO, capture_output=True, text=True, timeout=300)
        elapsed = time.time() - t0
        line = out.stdout.strip().splitlines()[-1] if out.stdout else ""
        print(f"client: {line}  ({elapsed:.1f}s incl. compile)")
        from distributed_bitcoinminer_tpu import native
        # The system scans [0, max_nonce+1]: the scheduler sends exclusive
        # bounds (upper += 1) but miners read Upper inclusively — the
        # reference's bound quirk, preserved for bit parity (scheduler.py
        # module docstring; test_conformance.py oracles the same way).
        want = native.scan_min_native(data, 0, max_nonce + 1)
        print(f"oracle: Result {want[0]} {want[1]}")
        ok = line == f"Result {want[0]} {want[1]}"
        print("MATCH" if ok else "MISMATCH")

        # Difficulty leg: same range with a ~2^-8-per-nonce target; the
        # miner must run the in-kernel early exit and the Result must be
        # the FIRST qualifying nonce (or the exact arg-min on a miss).
        target = 1 << 56
        t0 = time.time()
        out = subprocess.run(
            [sys.executable, "-m", "distributed_bitcoinminer_tpu.apps.client",
             f"localhost:{PORT}", data, str(max_nonce), str(target)],
            env=env, cwd=_REPO, capture_output=True, text=True, timeout=300)
        elapsed = time.time() - t0
        line = out.stdout.strip().splitlines()[-1] if out.stdout else ""
        print(f"client[target 2^56]: {line}  ({elapsed:.1f}s)")
        u_hash, u_nonce, u_found = native.scan_until_native(
            data, 0, max_nonce + 1, target)
        print(f"oracle[target 2^56]: Result {u_hash} {u_nonce} "
              f"(found={u_found})")
        ok_until = line == f"Result {u_hash} {u_nonce}"
        print("MATCH" if ok_until else "MISMATCH")
        return 0 if (ok and ok_until) else 1
    finally:
        for p in procs:
            p.kill()


if __name__ == "__main__":
    rc = main()
    sys.stdout.flush()
    os._exit(rc)
