#!/usr/bin/env python
"""CLI for the 10k-tenant control-plane load harness (ISSUE 11).

One storm leg (or a curve) on the socket-free detnet transport with
instant miners — the control plane is the only thing measured. Prints
one JSON line per leg.

Tier-1 mini-load leg (``scripts/tier1.sh``, ``DBM_TIER1_LOAD``):

    python scripts/loadharness.py --tenants 500 --assert-p99 30 \
        --assert-series 256

``--assert-*`` turns the run into a gate: every non-shed request must
complete, reply p99 must stay under the ceiling, and the process
metrics registry must not have grown an unbounded number of series
(per-tenant labels must collapse under the cardinality bound, not
explode) — exit 1 on any violation.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO)


def _series_count() -> int:
    from distributed_bitcoinminer_tpu.utils.metrics import registry
    snap = registry().snapshot()
    n = 0
    for family in ("counters", "gauges", "histograms", "ewmas"):
        n += len(snap.get(family, {}))
    return n


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--tenants", type=int, default=None,
                    help="tenant count (default: 1000, or the "
                         "workload's own population with --workload)")
    ap.add_argument("--replicas", type=int, default=None)
    ap.add_argument("--miners", type=int, default=None,
                    help="miner count (default 4; for a detnet "
                         "--replay the default models the pool from "
                         "the capture's own snapshots and an explicit "
                         "count overrides it)")
    ap.add_argument("--requests-per-tenant", type=int, default=None)
    ap.add_argument("--nonces", type=int, default=None)
    ap.add_argument("--max-queued", type=int, default=None)
    ap.add_argument("--recv-batch", type=int, default=None)
    ap.add_argument("--trace-sample", type=float, default=None)
    ap.add_argument("--qos-lazy", type=int, choices=(0, 1), default=None,
                    help="pin the lazy DRR walk (ISSUE 12 A/B; "
                         "default: on)")
    ap.add_argument("--procs", action="store_true",
                    help="drive the MULTI-PROCESS topology (real LSP "
                         "sockets, router + replica processes, fake "
                         "miner agents) instead of in-process detnet")
    ap.add_argument("--drivers", type=int, default=1,
                    help="shard the --procs storm driver across this "
                         "many OS processes (ISSUE 13 satellite; one "
                         "driver event loop tops out around O(500) "
                         "real UDP conns)")
    ap.add_argument("--workload", default=None,
                    choices=("mice_stampede", "tenant_churn",
                             "elephant_convoy"),
                    help="run ONE adversarial workload (ISSUE 13) "
                         "instead of the uniform storm; --adapt "
                         "picks the leg")
    ap.add_argument("--adapt", type=int, choices=(0, 1), default=0,
                    help="with --workload: 1 = the self-tuning "
                         "controllers, 0 = the static knob defaults")
    ap.add_argument("--capture-to", default=None, metavar="PATH",
                    help="arm the workload capture plane (ISSUE 15) "
                         "for the storm: the scheduler writes its "
                         "workload trace there (a --replay input)")
    ap.add_argument("--replay", default=None, metavar="PATH",
                    help="REPLAY a captured workload trace instead of "
                         "synthesizing a storm (ISSUE 15); prints the "
                         "measurement with the capture's own baseline "
                         "and the side-by-side fidelity verdict")
    ap.add_argument("--replay-speed", type=float, default=None,
                    help="time-warp factor for --replay (default: "
                         "DBM_REPLAY_SPEED, 1.0)")
    ap.add_argument("--assert-fidelity", action="store_true",
                    help="gate (--replay): exit 1 unless the replay "
                         "lands inside the stated fidelity bounds "
                         "(fidelity.within)")
    ap.add_argument("--timeout", type=float, default=300.0)
    ap.add_argument("--assert-p99", type=float, default=None,
                    help="gate: reply p99 ceiling in seconds")
    ap.add_argument("--assert-complete", type=float, default=None,
                    help="gate (adversarial workloads): minimum "
                         "completed/requests fraction — sheds are the "
                         "workload there, so the all-non-shed-complete "
                         "rule is replaced by this floor")
    ap.add_argument("--assert-series", type=int, default=None,
                    help="gate: max process metric series after the run")
    ap.add_argument("--assert-rollup", action="store_true",
                    help="gate (--procs): every cluster process must "
                         "have published a FRESH rollup snapshot blob "
                         "and the merged results_sent total must cover "
                         "every completed request (ISSUE 18)")
    args = ap.parse_args(argv)
    if args.assert_rollup and not args.procs:
        ap.error("--assert-rollup applies only to --procs runs (the "
                 "rollup plane is the multi-process state directory)")

    from distributed_bitcoinminer_tpu.apps.loadharness import (
        run_adversarial, run_load, run_load_procs, run_replay,
        run_replay_procs)
    before = _series_count()
    tenants = args.tenants if args.tenants is not None else 1000
    miners = args.miners if args.miners is not None else 4
    if args.replay is not None:
        # The CAPTURE owns the workload shape; storm-shape flags are
        # refused like --workload refuses them (silently dropping one
        # would print JSON that looks like the requested configuration
        # was measured).
        for flag, value in (("--workload", args.workload),
                            ("--tenants", args.tenants),
                            ("--requests-per-tenant",
                             args.requests_per_tenant),
                            ("--nonces", args.nonces),
                            ("--max-queued", args.max_queued),
                            ("--capture-to", args.capture_to),
                            ("--recv-batch", args.recv_batch),
                            ("--trace-sample", args.trace_sample),
                            ("--qos-lazy", args.qos_lazy),
                            ("--drivers", args.drivers
                             if args.drivers != 1 else None)):
            if value is not None:
                ap.error(f"{flag} does not apply to --replay runs "
                         f"(the capture owns the workload shape)")
        if args.procs:
            leg = run_replay_procs(
                args.replay,
                replicas=args.replicas if args.replicas is not None
                else 2,
                miners=miners, speed=args.replay_speed,
                timeout_s=args.timeout)
        else:
            if args.replicas is not None:
                ap.error("--replicas applies to --replay only with "
                         "--procs (the detnet replay is one replica)")
            # --miners forwards as an override; unset models the pool
            # from the capture's snapshots (silently dropping it was
            # the exact failure the refusal block above exists to
            # prevent — code review).
            leg = run_replay(args.replay, speed=args.replay_speed,
                             miners=args.miners,
                             timeout_s=args.timeout)
    elif args.workload is not None:
        # The workload SPEC owns replica topology, request counts,
        # nonce sizes, and the queue bound — a storm flag accepted
        # here and silently dropped would print JSON that looks like
        # the requested configuration was measured (review finding).
        for flag, value in (("--replicas", args.replicas),
                            ("--requests-per-tenant",
                             args.requests_per_tenant),
                            ("--nonces", args.nonces),
                            ("--max-queued", args.max_queued),
                            ("--drivers",
                             args.drivers if args.drivers != 1
                             else None),
                            ("--procs", args.procs or None)):
            if value is not None:
                ap.error(f"{flag} does not apply to --workload runs "
                         f"(the workload spec owns it)")
        # tenants=None keeps the workload's own population; an
        # explicit --tenants scales it down for smoke-sized runs.
        leg = run_adversarial(
            args.workload, adapt=bool(args.adapt),
            tenants=args.tenants,
            miners=miners, capture_path=args.capture_to,
            timeout_s=args.timeout)
    elif args.procs:
        if args.capture_to is not None:
            ap.error("--capture-to does not apply to --procs runs "
                     "(the capture plane is scheduler-resident; arm "
                     "DBM_CAPTURE in the replica processes' env)")
        leg = run_load_procs(
            tenants=tenants,
            replicas=args.replicas if args.replicas is not None else 1,
            miners=miners,
            requests_per_tenant=args.requests_per_tenant or 1,
            req_nonces=args.nonces or 256, drivers=args.drivers,
            timeout_s=args.timeout)
    else:
        leg = run_load(
            tenants=tenants,
            replicas=args.replicas if args.replicas is not None else 1,
            miners=miners,
            requests_per_tenant=args.requests_per_tenant or 1,
            req_nonces=args.nonces or 256,
            max_queued=args.max_queued
            if args.max_queued is not None else 4096,
            recv_batch=args.recv_batch, trace_sample=args.trace_sample,
            qos_lazy=(None if args.qos_lazy is None
                      else bool(args.qos_lazy)),
            capture_path=args.capture_to,
            timeout_s=args.timeout)
    after = _series_count()
    leg["metric_series"] = {"before": before, "after": after}
    print(json.dumps(leg, sort_keys=True), flush=True)

    rc = 0
    if args.workload is not None or args.replay is not None:
        # Adversarial workloads — and replays of shed-heavy captures —
        # SHED BY DESIGN: the no-loss rule is that every request was
        # either answered or shed with its conn closed, and
        # --assert-complete floors the answered fraction.
        expected = leg["requests"] - leg.get("shed_requests", 0)
    else:
        expected = leg["requests"] \
            - leg["shed_tenants"] * (args.requests_per_tenant or 1)
    if args.replay is not None and args.assert_fidelity:
        fid = leg.get("fidelity", {})
        if not fid.get("within"):
            print(f"LOAD_GATE: replay fidelity outside the stated "
                  f"bounds: {fid.get('violations')}", file=sys.stderr)
            rc = 1
    if leg.get("timed_out"):
        print("LOAD_GATE: storm timed out", file=sys.stderr)
        rc = 1
    if leg["completed"] < expected:
        print(f"LOAD_GATE: only {leg['completed']}/{expected} non-shed "
              f"requests completed", file=sys.stderr)
        rc = 1
    if args.assert_complete is not None and leg["requests"] and \
            leg["completed"] / leg["requests"] < args.assert_complete:
        print(f"LOAD_GATE: completed fraction "
              f"{leg['completed'] / leg['requests']:.3f} under the "
              f"{args.assert_complete} floor", file=sys.stderr)
        rc = 1
    if args.assert_p99 is not None and leg["p99_s"] is not None \
            and leg["p99_s"] > args.assert_p99:
        print(f"LOAD_GATE: p99 {leg['p99_s']}s over the "
              f"{args.assert_p99}s ceiling", file=sys.stderr)
        rc = 1
    if args.assert_series is not None and after > args.assert_series:
        print(f"LOAD_GATE: {after} metric series after the run "
              f"(bound {args.assert_series}) — unbounded label growth",
              file=sys.stderr)
        rc = 1
    if args.assert_rollup:
        ru = leg.get("rollup")
        expected_procs = 1 + leg.get("replicas", 0) + leg.get("miners", 0)
        if not isinstance(ru, dict) or "error" in ru:
            print(f"LOAD_GATE: no rollup summary in the leg "
                  f"(DBM_ROLLUP off, or aggregate failed: {ru})",
                  file=sys.stderr)
            rc = 1
        else:
            if ru.get("fresh", 0) < expected_procs:
                print(f"LOAD_GATE: only {ru.get('fresh')}/"
                      f"{expected_procs} cluster processes published a "
                      f"fresh rollup snapshot: {ru}", file=sys.stderr)
                rc = 1
            if ru.get("results_sent", 0) < leg["completed"]:
                print(f"LOAD_GATE: rollup results_sent "
                      f"{ru.get('results_sent')} under the "
                      f"{leg['completed']} completed requests the "
                      f"driver measured", file=sys.stderr)
                rc = 1
    return rc


if __name__ == "__main__":
    sys.exit(main())
