#!/usr/bin/env python
"""CLI for the 10k-tenant control-plane load harness (ISSUE 11).

One storm leg (or a curve) on the socket-free detnet transport with
instant miners — the control plane is the only thing measured. Prints
one JSON line per leg.

Tier-1 mini-load leg (``scripts/tier1.sh``, ``DBM_TIER1_LOAD``):

    python scripts/loadharness.py --tenants 500 --assert-p99 30 \
        --assert-series 256

``--assert-*`` turns the run into a gate: every non-shed request must
complete, reply p99 must stay under the ceiling, and the process
metrics registry must not have grown an unbounded number of series
(per-tenant labels must collapse under the cardinality bound, not
explode) — exit 1 on any violation.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO)


def _series_count() -> int:
    from distributed_bitcoinminer_tpu.utils.metrics import registry
    snap = registry().snapshot()
    n = 0
    for family in ("counters", "gauges", "histograms", "ewmas"):
        n += len(snap.get(family, {}))
    return n


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--tenants", type=int, default=1000)
    ap.add_argument("--replicas", type=int, default=1)
    ap.add_argument("--miners", type=int, default=4)
    ap.add_argument("--requests-per-tenant", type=int, default=1)
    ap.add_argument("--nonces", type=int, default=256)
    ap.add_argument("--max-queued", type=int, default=4096)
    ap.add_argument("--recv-batch", type=int, default=None)
    ap.add_argument("--trace-sample", type=float, default=None)
    ap.add_argument("--qos-lazy", type=int, choices=(0, 1), default=None,
                    help="pin the lazy DRR walk (ISSUE 12 A/B; "
                         "default: on)")
    ap.add_argument("--procs", action="store_true",
                    help="drive the MULTI-PROCESS topology (real LSP "
                         "sockets, router + replica processes, fake "
                         "miner agents) instead of in-process detnet")
    ap.add_argument("--timeout", type=float, default=300.0)
    ap.add_argument("--assert-p99", type=float, default=None,
                    help="gate: reply p99 ceiling in seconds")
    ap.add_argument("--assert-series", type=int, default=None,
                    help="gate: max process metric series after the run")
    args = ap.parse_args(argv)

    from distributed_bitcoinminer_tpu.apps.loadharness import (
        run_load, run_load_procs)
    before = _series_count()
    if args.procs:
        leg = run_load_procs(
            tenants=args.tenants, replicas=args.replicas,
            miners=args.miners,
            requests_per_tenant=args.requests_per_tenant,
            req_nonces=args.nonces, timeout_s=args.timeout)
    else:
        leg = run_load(
            tenants=args.tenants, replicas=args.replicas,
            miners=args.miners,
            requests_per_tenant=args.requests_per_tenant,
            req_nonces=args.nonces, max_queued=args.max_queued,
            recv_batch=args.recv_batch, trace_sample=args.trace_sample,
            qos_lazy=(None if args.qos_lazy is None
                      else bool(args.qos_lazy)),
            timeout_s=args.timeout)
    after = _series_count()
    leg["metric_series"] = {"before": before, "after": after}
    print(json.dumps(leg, sort_keys=True), flush=True)

    rc = 0
    expected = leg["requests"] \
        - leg["shed_tenants"] * args.requests_per_tenant
    if leg.get("timed_out"):
        print("LOAD_GATE: storm timed out", file=sys.stderr)
        rc = 1
    if leg["completed"] < expected:
        print(f"LOAD_GATE: only {leg['completed']}/{expected} non-shed "
              f"requests completed", file=sys.stderr)
        rc = 1
    if args.assert_p99 is not None and leg["p99_s"] is not None \
            and leg["p99_s"] > args.assert_p99:
        print(f"LOAD_GATE: p99 {leg['p99_s']}s over the "
              f"{args.assert_p99}s ceiling", file=sys.stderr)
        rc = 1
    if args.assert_series is not None and after > args.assert_series:
        print(f"LOAD_GATE: {after} metric series after the run "
              f"(bound {args.assert_series}) — unbounded label growth",
              file=sys.stderr)
        rc = 1
    return rc


if __name__ == "__main__":
    sys.exit(main())
