#!/usr/bin/env python
"""benchdiff — machine-readable diff of two bench artifacts (ISSUE 15).

The bench trajectory (``BENCH_r*.json`` / ``MULTICHIP_r*.json``) grew a
probe dict per PR but no comparator: "did detail.qos regress between
r05 and r06" was a human eyeballing two JSON trees. This script walks
two artifacts, pairs every numeric leaf by path, classifies each leaf
by its key name (higher-better like ``nps``/``admitted_per_s``,
lower-better like ``p99_s``/``cpu_s_per_request``, or informational —
configuration echoes and counts are never gated), and prints a
per-probe regression table.

    python scripts/benchdiff.py BENCH_r05.json BENCH_r06.json
    python scripts/benchdiff.py OLD.json NEW.json --threshold 0.25
    python scripts/benchdiff.py OLD.json NEW.json --json > diff.json

Exit codes: 0 no directional metric regressed past ``--threshold``
(default 0.20 = 20%), 1 at least one did (each flagged ``REGRESSED``
in the table), 2 usage/IO. Paths only in one artifact are listed as
added/removed, never gated — a new probe is not a regression.

Direction is classified by the LAST path segment (word-boundary
matching against the pattern lists below); anything unmatched is
``info``. Sample lists (``*_samples``, ``samples``) and obvious
config echoes are skipped entirely.

Rollup snapshots (ISSUE 18): an input that is a cluster rollup
document (``apps.rollup.aggregate`` / ``dbmtop --once --json`` output
— keys ``cluster`` + ``procs``) is flattened into diffable leaves
first: counters and gauges by metric key, EWMAs as ``value`` leaves,
histograms as ``p50``/``p99`` quantiles, plus freshness counts — so
two observability snapshots diff like two bench artifacts.
"""

from __future__ import annotations

import argparse
import json
import re
import sys

# Leaf-name patterns. Matched as whole words against the FINAL path
# segment (lowercased); first hit wins, higher-better checked first so
# e.g. "rate_gain" beats the lower-better "rate" guard below.
_HIGHER = (
    "nps", "value", "vs_baseline", "admitted_per_s", "speedup",
    "rate_gain", "dispatch_reduction", "efficiency", "throughput",
    "completed", "hit_ratio", "gain", "admitted_ratio",
    "devloop_speedup", "ttfh_speedup",
)
_LOWER = (
    "p50_s", "p99_s", "p50", "p99", "cpu_s_per_request", "makespan_s",
    "latency_s", "latency", "shed_rate", "regression", "compile_s",
    "elapsed_s", "overhead", "dispatches_per_mouse", "timed_s",
    "queue_wait_s", "shed_delta", "ttfh_s", "until_ttfh_s",
    "launches_per_span", "dispatches_per_span",
    "host_transfers_per_span", "host_bytes_per_span",
    # detail.federation (ISSUE 20): the federation tax and the
    # whole-cluster placement error both shrink when healthy.
    "overhead_ratio", "tracking_error",
    "flat_makespan_s", "federated_makespan_s",
)
#: Path segments that are configuration/noise, never metrics: the walk
#: prunes the whole subtree.
_SKIP_SEGMENTS = ("samples", "on_samples", "off_samples", "adapt_state",
                  "snapshot", "metrics", "hoist", "capture")
_SKIP_RE = re.compile(r"(^|_)(range|rounds|repeats|tenants|miners|"
                      r"replicas|batch|lanes|devices|depth|size|seed|"
                      r"count|lower|upper|warmup_s|interval|port|pid)"
                      r"(_|$)")


def _direction(segment: str) -> str:
    seg = segment.lower()
    for pat in _HIGHER:
        if seg == pat:
            return "higher"
    for pat in _LOWER:
        if seg == pat:
            return "lower"
    return "info"


def _leaves(obj, path=()):
    """(path_tuple, number) for every numeric leaf, pruning noise."""
    if isinstance(obj, dict):
        for key, val in obj.items():
            key = str(key)
            if key in _SKIP_SEGMENTS:
                continue
            yield from _leaves(val, path + (key,))
    elif isinstance(obj, bool):
        return
    elif isinstance(obj, (int, float)):
        if path and not _SKIP_RE.search(path[-1].lower()):
            yield path, float(obj)
    # Lists are samples/sweeps — per-element pairing across artifacts
    # is not stable, so they are never diffed.


def _hist_quantile(h: dict, q: float):
    """Quantile bound from the registry's cumulative-``le`` histogram
    shape (kept local: benchdiff imports nothing from the package)."""
    count = h.get("count", 0)
    if not count:
        return None
    need = q * count
    for bound, c in zip(h.get("le", ()), h.get("counts", ())):
        if c >= need:
            return float(bound)
    return None            # lands in the +Inf bucket: unbounded


def _is_rollup(doc) -> bool:
    return isinstance(doc, dict) and "cluster" in doc and "procs" in doc


def _flatten_rollup(doc: dict) -> dict:
    """Cluster rollup doc -> diffable leaves (ISSUE 18). The raw doc
    would mostly vanish into the ``snapshot``/``count`` skip rules;
    this pins the comparable surface explicitly."""
    procs = doc.get("procs", [])
    cl = doc.get("cluster", {})
    metrics = {}
    for section in ("counters", "gauges"):
        for key, v in cl.get(section, {}).items():
            if isinstance(v, (int, float)) and not isinstance(v, bool):
                metrics[key] = v
    for key, e in cl.get("ewmas", {}).items():
        if isinstance(e, dict) and isinstance(e.get("value"),
                                              (int, float)):
            metrics[key] = {"value": e["value"]}
    for key, h in cl.get("histograms", {}).items():
        if not isinstance(h, dict):
            continue
        entry = {}
        for name, q in (("p50", 0.5), ("p99", 0.99)):
            qv = _hist_quantile(h, q)
            if qv is not None:
                entry[name] = qv
        if entry:
            metrics[key] = entry
    return {"rollup": {
        "procs_total": len(procs),
        "procs_fresh": sum(1 for p in procs
                           if p.get("status") == "fresh"),
        "series_overflow": cl.get("series_overflow", 0),
        "cluster": metrics,
    }}


def diff(old: dict, new: dict, threshold: float) -> dict:
    old_leaves = dict(_leaves(old))
    new_leaves = dict(_leaves(new))
    rows = []
    regressions = 0
    for path in sorted(set(old_leaves) & set(new_leaves)):
        a, b = old_leaves[path], new_leaves[path]
        direction = _direction(path[-1])
        if a == 0:
            change = None
        else:
            change = (b - a) / abs(a)
        verdict = "info"
        if direction != "info" and change is not None:
            worse = change < -threshold if direction == "higher" \
                else change > threshold
            better = change > threshold if direction == "higher" \
                else change < -threshold
            verdict = ("REGRESSED" if worse
                       else "improved" if better else "ok")
            if worse:
                regressions += 1
        rows.append({"path": "/".join(path), "old": a, "new": b,
                     "change": round(change, 4)
                     if change is not None else None,
                     "direction": direction, "verdict": verdict})
    return {
        "threshold": threshold,
        "rows": rows,
        "regressions": regressions,
        "added": sorted("/".join(p)
                        for p in set(new_leaves) - set(old_leaves)),
        "removed": sorted("/".join(p)
                          for p in set(old_leaves) - set(new_leaves)),
    }


def _fmt(v: float) -> str:
    if v == int(v) and abs(v) < 1e15:
        return str(int(v))
    return f"{v:.6g}"


def print_table(result: dict, all_rows: bool) -> None:
    rows = [r for r in result["rows"]
            if all_rows or r["verdict"] != "info"]
    if rows:
        width = max(len(r["path"]) for r in rows)
        print(f"{'metric':<{width}}  {'old':>12}  {'new':>12}  "
              f"{'change':>8}  verdict")
        for r in rows:
            pct = (f"{r['change'] * 100:+.1f}%"
                   if r["change"] is not None else "n/a")
            print(f"{r['path']:<{width}}  {_fmt(r['old']):>12}  "
                  f"{_fmt(r['new']):>12}  {pct:>8}  {r['verdict']}")
    else:
        print("no comparable directional metrics")
    for key in ("added", "removed"):
        if result[key]:
            print(f"{key} ({len(result[key])}): "
                  + ", ".join(result[key][:8])
                  + (" ..." if len(result[key]) > 8 else ""))
    print(f"BENCHDIFF_REGRESSIONS={result['regressions']} "
          f"(threshold {result['threshold'] * 100:.0f}%)")


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="benchdiff", description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter)
    ap.add_argument("old")
    ap.add_argument("new")
    ap.add_argument("--threshold", type=float, default=0.20,
                    help="regression fraction past which a directional "
                         "metric fails the diff (default 0.20)")
    ap.add_argument("--all", action="store_true",
                    help="print informational rows too, not only "
                         "directional metrics")
    ap.add_argument("--json", action="store_true",
                    help="emit the diff as one JSON object instead of "
                         "a table")
    args = ap.parse_args(argv)
    try:
        with open(args.old, encoding="utf-8") as fh:
            old = json.load(fh)
        with open(args.new, encoding="utf-8") as fh:
            new = json.load(fh)
    except (OSError, ValueError) as exc:
        print(f"benchdiff: {exc}", file=sys.stderr)
        return 2
    if _is_rollup(old):
        old = _flatten_rollup(old)
    if _is_rollup(new):
        new = _flatten_rollup(new)
    result = diff(old, new, args.threshold)
    if args.json:
        print(json.dumps(result, sort_keys=True))
    else:
        print_table(result, args.all)
    return 1 if result["regressions"] else 0


if __name__ == "__main__":
    sys.exit(main())
