#!/usr/bin/env python
"""On-chip kernel tuning harness (run manually on a real TPU).

Three measurements, each printed as one line:

1. VPU u32 ceiling — a synthetic Pallas kernel issuing a pure
   rotate-xor-add chain (SHA-round-shaped ops, no memory traffic) to
   estimate attainable uint32 ops/s. Divides into the ~3.2k ops/nonce of
   one SHA-256 compression to bound the nonce-rate ceiling on this chip.
2. rows sweep — the real kernel at a fixed span with varying sublane
   counts (grid-step size), per-call blocked timing.
3. tier waterfall — jnp vs pallas at the bench geometry.

Usage: python scripts/tpu_tune.py [span_log2]   (default 24)
"""

from __future__ import annotations

import functools
import os
import sys
import time

import numpy as np


def main() -> int:
    span_log2 = int(sys.argv[1]) if len(sys.argv) > 1 else 24
    total = 1 << span_log2

    import jax
    import jax.numpy as jnp
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    sys.path.insert(
        0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
    from distributed_bitcoinminer_tpu.ops.search import search_span
    from distributed_bitcoinminer_tpu.ops.sha256_host import sha256_midstate
    from distributed_bitcoinminer_tpu.ops.sha256_jnp import build_tail_template
    from distributed_bitcoinminer_tpu.ops.sha256_pallas import (
        pallas_geometry, pallas_search_span)

    dev = jax.devices()[0]
    print(f"device: {dev} platform={dev.platform}", flush=True)

    # --- 1. VPU u32 ceiling ------------------------------------------------
    OPS_PER_ITER = 6 * 8   # 8 chains x (2 shifts + or + xor + 2 adds)
    ITERS = 2000

    def vpu_kernel(o_ref):
        xs = [jax.lax.broadcasted_iota(jnp.uint32, (8, 128), 1)
              + np.uint32(i) for i in range(8)]

        def body(i, xs):
            out = []
            for x in xs:
                r = (x >> np.uint32(7)) | (x << np.uint32(25))
                out.append((r ^ x) + (i.astype(jnp.uint32) + x))
            return tuple(out)

        xs = jax.lax.fori_loop(0, ITERS, body, tuple(xs))
        acc = xs[0]
        for x in xs[1:]:
            acc = acc ^ x
        o_ref[...] = acc

    grid_steps = 256
    f = pl.pallas_call(
        vpu_kernel,
        out_shape=jax.ShapeDtypeStruct((8, 128), jnp.uint32),
        grid=(grid_steps,),
        out_specs=pl.BlockSpec((8, 128), lambda s: (0, 0),
                               memory_space=pltpu.VMEM),
    )
    jf = jax.jit(f)
    jax.block_until_ready(jf())
    best = min(_timed(jf) for _ in range(3))
    ops = 8 * 128 * OPS_PER_ITER * ITERS * grid_steps
    print(f"vpu_u32_ceiling: {ops / best / 1e12:.2f} Tops/s "
          f"(=> ~{ops / best / 3.2e3 / 1e6:.0f} Mnonce/s SHA bound)",
          flush=True)

    # --- 2/3. real kernel -------------------------------------------------
    data = "cmu440"
    prefix = data.encode() + b" 2"
    midstate, tail = sha256_midstate(prefix)
    k = 9
    template = build_tail_template(tail, k, len(prefix) + k)
    ms = np.asarray(midstate, np.uint32)
    tp = template.astype(np.uint32)

    def rows_sweep(ms_a, tp_a, rem_a, label):
        for rows in (8, 16, 32, 64):
            nst = -(-total // (rows * 128))
            call = functools.partial(
                pallas_search_span, ms_a, tp_a, np.uint32(0), np.uint32(0),
                np.uint32(total - 1), rem=rem_a, k=k, rows=rows, nsteps=nst)
            jax.block_until_ready(call())
            best_s = min(_timed(call) for _ in range(3))
            print(f"pallas {label}rows={rows:3d}: "
                  f"{total / best_s / 1e6:8.1f} Mnonce/s", flush=True)

    rows_sweep(ms, tp, len(tail), "")

    batch = 1 << 20
    nb = -(-total // batch)
    jcall = functools.partial(
        search_span, ms, tp, np.uint32(0), np.uint32(0),
        np.uint32(total - 1), rem=len(tail), k=k, batch=batch, nbatches=nb)
    jax.block_until_ready(jcall())
    best = min(_timed(jcall) for _ in range(3))
    print(f"jnp batch=2^20 : {total / best / 1e6:8.1f} Mnonce/s", flush=True)

    rows, nsteps = pallas_geometry(total)
    print(f"default geometry: rows={rows} nsteps={nsteps}", flush=True)

    # --- 4. until-mode characterization (r4 in-kernel early exit) ---------
    from distributed_bitcoinminer_tpu.ops.sha256_pallas import (
        pallas_search_span_until)

    def ucall(t_hi, t_lo):
        return functools.partial(
            pallas_search_span_until, ms, tp, np.uint32(0), np.uint32(0),
            np.uint32(total - 1), np.uint32(t_hi), np.uint32(t_lo),
            rem=len(tail), k=k, rows=rows, nsteps=nsteps)

    # (a) miss path (target 0 never hits): the until kernel's flag
    # bookkeeping overhead vs the plain argmin kernel above.
    miss = ucall(0, 0)
    jax.device_get(miss())
    best = min(_timed(miss) for _ in range(3))
    print(f"until miss     : {total / best / 1e6:8.1f} Mnonce/s "
          "(flag-bookkeeping overhead vs argmin rows line)", flush=True)

    # (b) hit at step 0 (all-ones target qualifies every lane): total
    # time = dispatch + ONE compute step + (nsteps-1) skipped steps, so
    # this bounds the skipped-step cost — the number that decides whether
    # until-mode sub-dispatches ever need a size cap. The axon tunnel
    # contributes a ~35-100 ms floor; with 2^29 lanes (262k steps) a
    # 1 µs skip would show as ~0.26 s on top of it.
    hit = ucall(0xFFFFFFFF, 0xFFFFFFFF)
    jax.device_get(hit())
    best = min(_timed(hit) for _ in range(3))
    print(f"until hit@step0: {best * 1e3:8.2f} ms total over {nsteps} "
          f"steps -> <= {best / max(1, nsteps - 1) * 1e6:.2f} us/skipped "
          "step incl. tunnel floor", flush=True)

    # --- 5. 2-block-tail rows sweep (VERDICT r4 weak 5) -------------------
    # The rows=16 sweet spot above was measured on 1-block tails only; a
    # long message pushes the padded tail into a second SHA block (2
    # device compressions per nonce instead of 1) with different
    # VMEM/register pressure per step — the optimum may shift.
    long_data = "x" * 57          # 58B tail rem (incl. separator) -> 2 blocks
    lprefix = long_data.encode() + b" "
    lmid, ltail = sha256_midstate(lprefix)
    ltp = build_tail_template(ltail, k, len(lprefix) + k).astype(np.uint32)
    assert ltp.shape[0] == 2, f"want a 2-block tail, got {ltp.shape[0]}"
    rows_sweep(np.asarray(lmid, np.uint32), ltp, len(ltail), "2blk ")
    return 0


def _timed(fn) -> float:
    import jax
    t0 = time.perf_counter()
    # Force a literal host transfer: under the axon tunnel
    # block_until_ready returns before the computation finishes (round-3
    # finding — it timed a 2^24 SHA scan at 0.1 ms), only device_get
    # actually synchronizes.
    jax.device_get(fn())
    return time.perf_counter() - t0


if __name__ == "__main__":
    raise SystemExit(main())
