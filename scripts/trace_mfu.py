#!/usr/bin/env python
"""Trace-backed MFU for the pallas argmin kernel (VERDICT r3 task 4).

Round 3's BASELINE claimed "~3.3k u32 ops/nonce => ~4.3e12 op/s ~= VPU
roofline" from a hand count. This script replaces both factors with
measured artifacts:

1. **Op count** — a census of the kernel's own traced jaxpr (the exact
   program Mosaic lowers, not a hand model): every vector-shaped
   arithmetic/select/compare eqn per lane, with the 4-iteration
   schedule fori_loop weighted by its trip count.
2. **Step time** — a `jax.profiler` xplane trace of one 2^29-lane
   search on the real chip: device-plane busy time for the kernel
   events, window occupancy, and nonces/s from device time (not wall
   clock, which includes the axon tunnel).

Usage:
  python scripts/trace_mfu.py census        # CPU-safe, no chip needed
  python scripts/trace_mfu.py trace [span_log2=29]   # real chip

Exits via os._exit like bench.py (axon finalizer hang, round 3).
"""

from __future__ import annotations

import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

_VECTOR_ARITH = {
    "add", "sub", "mul", "xor", "or", "and", "not",
    "shift_left", "shift_right_logical", "shift_right_arithmetic",
    "rem", "div", "select_n", "lt", "le", "gt", "ge", "eq", "ne",
    "convert_element_type", "max", "min",
}


def _count_jaxpr(jaxpr, lane_shape, while_trip: int = 4) -> int:
    """Vector-op eqns per grid step, weighting loop bodies by trip count.

    Scalar eqns (SMEM reads, index math) are excluded by the lane-shape
    filter — only ops producing a full (rows, 128) register tile count.
    """
    jaxpr = getattr(jaxpr, "jaxpr", jaxpr)
    total = 0
    for eqn in jaxpr.eqns:
        prim = eqn.primitive.name
        if prim == "pallas_call":
            total += _count_jaxpr(eqn.params["jaxpr"], lane_shape,
                                  while_trip)
            continue
        if prim in ("closed_call", "custom_jvp_call", "pjit", "jit"):
            inner = eqn.params.get("jaxpr") or eqn.params.get("call_jaxpr")
            if inner is not None:
                total += _count_jaxpr(inner, lane_shape, while_trip)
            continue
        if prim == "while":
            # The 16-round schedule fori_loop lowers to while; its trip
            # count is static but not recoverable from the jaxpr, so the
            # caller supplies it (4 for the rolled kernel's fori(0,4),
            # 3 for the peeled kernel's fori(1,4)).
            total += while_trip * _count_jaxpr(
                eqn.params["body_jaxpr"], lane_shape, while_trip)
            continue
        if prim == "scan":
            total += eqn.params["length"] * _count_jaxpr(
                eqn.params["jaxpr"], lane_shape, while_trip)
            continue
        if prim == "cond":
            # pl.when branches: count the taken (non-trivial) branch.
            total += max(_count_jaxpr(b, lane_shape, while_trip)
                         for b in eqn.params["branches"])
            continue
        if prim in _VECTOR_ARITH and any(
                getattr(v.aval, "shape", ()) == lane_shape
                for v in eqn.outvars):
            total += 1
    return total


def census() -> dict:
    """Exact per-lane u32 op count of one kernel grid step, from the
    kernel's traced jaxpr (interpret=True traces the identical program
    Mosaic lowers on-chip; only the backend differs)."""
    import jax
    jax.config.update("jax_platforms", "cpu")
    import numpy as np

    from distributed_bitcoinminer_tpu.ops.sha256_host import sha256_midstate
    from distributed_bitcoinminer_tpu.ops.sha256_jnp import build_tail_template
    from distributed_bitcoinminer_tpu.ops.sha256_pallas import (
        _LANES, _ROWS_MAX, pallas_search_span)

    from distributed_bitcoinminer_tpu.ops.sha256_pallas import peel_enabled

    prefix = b"cmu440 2"          # d=10, k=9 block: the bench geometry
    midstate, tail = sha256_midstate(prefix)
    template = build_tail_template(tail, 9, len(prefix) + 9)
    rows = _ROWS_MAX
    peel = peel_enabled()         # DBM_PEEL: census the peeled variant

    def one_step():
        return pallas_search_span(
            np.asarray(midstate, dtype=np.uint32), template,
            np.uint32(0), np.uint32(0), np.uint32(rows * _LANES - 1),
            rem=len(tail), k=9, rows=rows, nsteps=1, interpret=True,
            peel=peel)

    jaxpr = jax.make_jaxpr(one_step)()
    # The schedule fori_loop's static trip count: 4 blocks rolled, or 3
    # with block 0 peeled into straight-line rounds (sha256_pallas).
    per_step = _count_jaxpr(jaxpr.jaxpr, (rows, _LANES),
                            while_trip=3 if peel else 4)
    lanes = rows * _LANES
    return {"vector_ops_per_step": per_step,
            "lanes_per_step": lanes,
            "ops_per_nonce": per_step,  # one (rows,128) eqn = 1 op/lane
            "nblocks": template.shape[0],
            "peel": peel}


def parse_xplane(trace_dir: str, host_fallback: bool = False) -> dict:
    """Device-plane kernel time out of a jax.profiler trace directory.

    ``host_fallback`` (trace-dev only): walk the ``/host:`` planes when
    no device plane exists — NEVER set on a chip run, where a missing
    device plane must surface as the unmistakable all-zero report, not
    as host time dressed up like kernel time."""
    import glob

    from tensorflow.tsl.profiler.protobuf import xplane_pb2

    pbs = glob.glob(os.path.join(trace_dir, "**", "*.xplane.pb"),
                    recursive=True)
    if not pbs:
        raise FileNotFoundError(f"no .xplane.pb under {trace_dir}")
    xs = xplane_pb2.XSpace()
    with open(sorted(pbs)[-1], "rb") as fh:
        xs.ParseFromString(fh.read())
    device_planes = [p for p in xs.planes
                     if "TPU" in p.name or "/device:" in p.name.lower()]
    out = {"trace_file": sorted(pbs)[-1], "planes": {}}
    if not device_planes and host_fallback:
        # CPU runs (the trace-dev tool-validation mode) emit only host
        # planes; walk those instead so the event aggregation below runs
        # against real data, and say so — host busy time is NOT a device
        # kernel measurement.
        device_planes = [p for p in xs.planes
                         if p.name.startswith("/host:") and p.lines]
        out["plane_kind"] = "host-fallback"
    for plane in device_planes:
        per_op: dict[str, int] = {}
        window_lo, window_hi = None, None
        for line in plane.lines:
            for ev in line.events:
                name = plane.event_metadata[ev.metadata_id].name
                per_op[name] = per_op.get(name, 0) + ev.duration_ps
                lo = line.timestamp_ns * 1000 + ev.offset_ps
                hi = lo + ev.duration_ps
                window_lo = lo if window_lo is None else min(window_lo, lo)
                window_hi = hi if window_hi is None else max(window_hi, hi)
        out["planes"][plane.name] = {
            # FULL per-op map (ms) — truncating here would skew the MFU
            # the script exists to measure (code-review r4).
            "busy_ms": {n: p / 1e9 for n, p in sorted(
                per_op.items(), key=lambda kv: -kv[1])},
            "window_ms": ((window_hi - window_lo) / 1e9
                          if window_lo is not None else 0.0),
        }
    return out


_KERNEL_EVENT = ("pallas", "sha256", "custom-call", "custom_call")


def kernel_busy_ms(planes: dict) -> tuple[float, float, bool]:
    """(kernel_ms, total_busy_ms, matched): kernel events selected by
    name; ``matched=False`` means no event name matched the kernel
    patterns and kernel_ms fell back to total busy time — inspect the
    per-op map before trusting the headline number."""
    best_kernel, best_total, matched = 0.0, 0.0, False
    for plane in planes["planes"].values():
        total = sum(plane["busy_ms"].values())
        kern = sum(ms for name, ms in plane["busy_ms"].items()
                   if any(pat in name.lower() for pat in _KERNEL_EVENT))
        if total > best_total:
            best_total = total
            best_kernel, matched = (kern, True) if kern else (total, False)
    return best_kernel, best_total, matched


def trace(span_log2: int = 29, dev_cpu: bool = False) -> dict:
    """One pallas search of 2^span_log2 lanes on the real chip under the
    profiler; reports census MFU with device-measured step time.

    ``dev_cpu`` (the ``trace-dev`` CLI mode) is a TOOL-VALIDATION run:
    it skips the chip gate, pins this process to CPU, and uses the jnp
    tier on a small span — proving the profiler capture, the xplane
    proto parse, and the report plumbing end-to-end without hardware
    (round 5: the trace mode was built during a tunnel outage and must
    work first try when the chip returns). Its numbers are NOT kernel
    measurements; ``kernel_events_matched`` is expected False on CPU.
    """
    import json
    import tempfile
    import time

    from distributed_bitcoinminer_tpu.utils._env import float_env
    from distributed_bitcoinminer_tpu.utils.config import (CHIP_PLATFORMS,
                                                           probe_backend)
    if not dev_cpu:
        probe = probe_backend(float_env("DBM_BENCH_INIT_TIMEOUT", 300.0))
        if "error" in probe or probe.get("platform") not in CHIP_PLATFORMS:
            report = {"error": "chip unreachable", "probe": probe}
            print(json.dumps(report))
            return report

    import jax

    if dev_cpu:
        jax.config.update("jax_platforms", "cpu")

    from distributed_bitcoinminer_tpu.models import NonceSearcher

    # Census in a SUBPROCESS: census() pins jax_platforms='cpu'
    # process-wide (its tracing must stay off the chip), which in this
    # process would flip the "real chip" search below into the Mosaic
    # interpreter (code-review r4).
    import subprocess
    proc = subprocess.run(
        [sys.executable, os.path.abspath(__file__), "census"],
        capture_output=True, text=True, timeout=600)
    if proc.returncode != 0:
        raise RuntimeError(f"census subprocess failed:\n"
                           f"{proc.stderr.strip()[-800:]}")
    # The child prints one pretty-printed JSON object; parse the whole
    # stream (a last-line parse would read just the closing brace).
    c = json.loads(proc.stdout)
    searcher = NonceSearcher("cmu440", batch=1 << 13 if dev_cpu else 1 << 20,
                             tier="jnp" if dev_cpu else "pallas")
    lo = 10_000_000 if dev_cpu else 2_000_000_000
    hi = lo + (1 << span_log2) - 1
    searcher.search(lo, hi)               # warm every signature
    trace_dir = tempfile.mkdtemp(prefix="dbm_mfu_")
    t0 = time.time()
    with jax.profiler.trace(trace_dir):
        got = searcher.search(lo, hi)
    wall = time.time() - t0
    planes = parse_xplane(trace_dir, host_fallback=dev_cpu)
    kernel_ms, total_ms, matched = kernel_busy_ms(planes)
    lanes = 1 << span_log2
    report = {
        "result": [int(x) for x in got],
        "span_lanes": lanes,
        "wall_s": wall,
        "kernel_device_ms": kernel_ms,
        "kernel_events_matched": matched,
        "total_device_busy_ms": total_ms,
        "nonces_per_s_device": lanes / (kernel_ms / 1e3) if kernel_ms else 0,
        "ops_per_nonce_census": c["ops_per_nonce"],
        "u32_ops_per_s": (c["ops_per_nonce"] * lanes / (kernel_ms / 1e3)
                          if kernel_ms else 0),
        "trace": planes,
    }
    print(json.dumps(report, indent=2, default=str))
    return report


if __name__ == "__main__":
    mode = sys.argv[1] if len(sys.argv) > 1 else "census"
    rc = 0
    try:
        if mode == "census":
            import json
            print(json.dumps(census(), indent=2))
        elif mode in ("trace", "trace-dev"):
            dev = mode == "trace-dev"
            report = trace(int(sys.argv[2]) if len(sys.argv) > 2
                           else (17 if dev else 29), dev_cpu=dev)
            rc = 2 if "error" in report else 0  # match chip_e2e's contract
        else:
            # A typo must not fall into the expensive real-chip path.
            print(f"unknown mode {mode!r}; usage: trace_mfu.py "
                  "census | trace [span_log2] | trace-dev [span_log2]",
                  file=sys.stderr)
            rc = 1
    except Exception as exc:  # noqa: BLE001 — every path must reach the
        # hard exit below: an uncaught exception after jax touched the
        # axon backend would hang in interpreter-shutdown finalizers.
        print(f"trace_mfu failed: {exc!r}"[:800], file=sys.stderr)
        rc = 1
    sys.stdout.flush()
    sys.stderr.flush()
    os._exit(rc)
